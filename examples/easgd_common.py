"""Shared pieces for the AsyncEA (EASGD) client/server/tester trio —
the counterpart of the reference's shared examples/Model.lua +
examples/Data.lua used by EASGD_{server,client,tester}.lua.

Every role builds the SAME model with the SAME seed (ref Model.lua:17
``torch.manualSeed(0)``) and then the server's initial center broadcast makes
init exact (ref AsyncEA.lua:150-160).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from common import setup_platform  # noqa: E402  (re-export)


def build_model_and_data(opt, partition: int = 0, partitions: int = 1):
    """Model + partitioned data (ref Model.lua / Data.lua).  ``--model cifar``
    is the reference's convnet; ``--model mnist`` is the cheap CNN for smoke
    runs on CPU."""
    from jax import random

    from distlearn_tpu.data import (load_npz, make_dataset, synthetic_cifar10,
                                    synthetic_mnist)
    from distlearn_tpu.models import cifar_convnet, mnist_cnn

    synth = synthetic_cifar10 if opt.model == "cifar" else synthetic_mnist
    if opt.data:
        x, y, nc = load_npz(opt.data)
    else:
        x, y, nc = synth(opt.numExamples, seed=opt.seed)
    ds = make_dataset(x, y, nc, partition=partition, partitions=partitions)

    model = cifar_convnet() if opt.model == "cifar" else mnist_cnn()
    params, mstate = model.init(random.PRNGKey(opt.seed))
    return model, params, mstate, ds, nc


DATA_FLAGS = {
    "data": ("", "path to .npz dataset (default: synthetic)"),
    "numExamples": (2048, "synthetic dataset size"),
    "model": ("cifar", "model family: cifar (reference convnet) | mnist"),
}


def obs_setup(opt):
    """Wire ``--obsLog``/``--obsPort``/``--obsTrace``
    (utils.flags.OBS_FLAGS): start the span spill, the /metrics +
    /healthz endpoint, and/or cross-process trace propagation.  Returns
    the HTTP server handle (or None) for :func:`obs_finish`."""
    if getattr(opt, "obsTrace", 0):
        from distlearn_tpu.obs import trace
        trace.set_propagate(True)
    if not (opt.obsLog or opt.obsPort):
        return None
    from distlearn_tpu import obs
    if opt.obsLog:
        obs.set_spill(opt.obsLog)
    return obs.start_http_server(opt.obsPort) if opt.obsPort else None


def obs_finish(opt, http=None):
    """End-of-run telemetry: one registry snapshot appended to the run's
    JSONL (the counters tools/diststat.py reads) and endpoint shutdown."""
    if not (opt.obsLog or http):
        return
    from distlearn_tpu import obs
    if opt.obsLog:
        obs.write_snapshot(opt.obsLog)
        obs.set_spill(None)
    if http is not None:
        http.close()
