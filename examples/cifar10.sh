#!/bin/bash
# Reference parity: examples/cifar10.sh (2 CPU nodes).
cd "$(dirname "$0")"
python cifar10.py --numNodes 2 --numEpochs 2 "$@"
