#!/usr/bin/env python
"""Distributed ResNet-50 v1.5 training with AllReduceSGD — the ImageNet-scale
stretch config (BASELINE.md "Benchmark configs to reproduce" row 5; the
reference tops out at the CIFAR convnet, examples/cifar10.lua).

The 25.6M-parameter / 161-leaf pytree is where gradient bucketing matters:
``--bucketMB`` packs gradients into flat buckets so the cross-node psum and
the fused Pallas SGD update stream over HBM once per bucket instead of once
per tensor (distlearn_tpu/ops/flatten.py).

Run:  python examples/resnet50.py --numNodes 8 --batchSize 256
      python examples/resnet50.py --tpu --numNodes 1 --batchSize 256 --bf16
"""

from __future__ import annotations

from common import setup_platform, resolve_num_nodes, device_stream
from distlearn_tpu.utils.flags import (parse_flags, CKPT_FLAGS,
                                       NODE_FLAGS, TRAIN_FLAGS)


def main():
    opt = parse_flags("Train ResNet-50 v1.5.", {
        **NODE_FLAGS,
        **TRAIN_FLAGS,
        "batchSize": (256, "global batch size"),
        "imageSize": (224, "square image edge"),
        "numClasses": (1000, "label count"),
        "numExamples": (2048, "synthetic dataset size"),
        "data": ("", "path to .npz with x [N,S,S,3]/y (default: synthetic)"),
        **CKPT_FLAGS,
        "bf16": (False, "bfloat16 compute (MXU path)"),
        "bucketMB": (16, "gradient bucket size in MiB (0 = one bucket)"),
        "stepsPerEpoch": (0, "cap steps per epoch (0 = full epoch)"),
        "deviceData": (False, "dataset resident in device memory, batches "
                              "gathered on-device (see cifar10.py; needs "
                              "numExamples * imageSize^2 * 12B of HBM)"),
    })
    setup_platform(opt.numNodes, opt.tpu)

    import jax
    import jax.numpy as jnp
    from jax import random

    from distlearn_tpu.data import (DeviceDataset, PermutationSampler,
                                    load_npz, make_dataset,
                                    synthetic_imagenet)
    from distlearn_tpu.models import param_count, resnet50
    from distlearn_tpu.parallel.mesh import MeshTree
    from distlearn_tpu.train import (build_sgd_step, build_sync_step,
                                     init_train_state, reduce_confusion)
    from distlearn_tpu.utils import checkpoint as ckpt
    from distlearn_tpu.utils import metrics as M
    from distlearn_tpu.utils.logging import root_print
    from distlearn_tpu.utils.profiling import StepTimer

    log = root_print(0)
    tree = MeshTree(num_nodes=resolve_num_nodes(opt.numNodes, opt.tpu))
    log(f"mesh: {tree.num_nodes} nodes on {jax.devices()[0].platform}")

    if opt.data:
        x, y, nc = load_npz(opt.data)
    else:
        x, y, nc = synthetic_imagenet(opt.numExamples, opt.imageSize,
                                      opt.numClasses, seed=opt.seed)
    ds = make_dataset(x, y, nc)
    if opt.deviceData:
        from jax.sharding import NamedSharding, PartitionSpec as P
        dds = DeviceDataset(
            ds.x, ds.y, nc, sharding=NamedSharding(tree.mesh, P()),
            out_sharding=NamedSharding(tree.mesh, P(tree.axis_name)))

    def train_stream(sampler):
        if opt.deviceData:
            return dds.batches(sampler, opt.batchSize)
        return device_stream(tree, ds, sampler, opt.batchSize)

    model = resnet50(num_classes=nc, image_size=opt.imageSize,
                     compute_dtype=jnp.bfloat16 if opt.bf16 else None)
    ts = init_train_state(model, tree, random.PRNGKey(opt.seed), nc)
    log(f"resnet50: {param_count(ts.params):,} params, "
        f"{len(jax.tree_util.tree_leaves(ts.params))} leaves, "
        f"bucket {opt.bucketMB} MiB")
    step = build_sgd_step(
        model, tree, lr=opt.learningRate,
        max_bucket_bytes=opt.bucketMB * 1024 * 1024 if opt.bucketMB else None)
    sync = build_sync_step(tree)

    start_epoch = 1
    if opt.resume and opt.save and ckpt.latest_step(opt.save) is not None:
        restorable = {"params": ts.params, "model_state": ts.model_state}
        restored, meta = ckpt.restore_checkpoint(opt.save, restorable)
        ts = ts._replace(params=restored["params"],
                         model_state=restored["model_state"])
        start_epoch = meta["step"] + 1
        log(f"resumed from epoch {meta['step']}")

    timer = StepTimer()
    # async writer: epoch N+1 trains while epoch N's npz hits disk (a
    # ResNet-50 checkpoint is ~100 MB — a synchronous write stalls the mesh)
    with ckpt.AsyncCheckpointer(opt.save or ".", keep=3) as saver:
        for epoch in range(start_epoch, opt.numEpochs + 1):
            sampler = PermutationSampler(ds.size, seed=opt.seed + epoch)
            timer.reset_window()   # epoch-boundary eval/ckpt is not a step
            for i, (bx, by) in enumerate(train_stream(sampler)):
                timer.tick()
                ts, loss = step(ts, bx, by)
                if opt.stepsPerEpoch and i + 1 >= opt.stepsPerEpoch:
                    break
            ts = sync(ts)
            cm = reduce_confusion(ts.cm)
            ts = ts._replace(cm=jax.tree_util.tree_map(lambda c: c * 0, ts.cm))
            log(f"epoch {epoch}: loss {float(loss):.4f} "
                f"train {M.format_confusion(cm)} "
                f"({timer.steps_per_sec():.2f} steps/s)")
            if opt.save:
                saver.save(epoch,
                           {"params": ts.params, "model_state": ts.model_state},
                           metadata={"epoch": epoch})
    jax.block_until_ready(ts.params)
    log("done")


if __name__ == "__main__":
    main()
