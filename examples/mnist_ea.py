#!/usr/bin/env python
"""Distributed MNIST training with elastic averaging (AllReduceEA) — the
TPU-native counterpart of examples/mnist-ea.lua.

Reference cadence (SURVEY.md §3.2): one initial parameter sync
(mnist-ea.lua:63), per-step local SGD — collective-free — then every
``tau``-th step the fused elastic round (mnist-ea.lua:110,
lua/AllReduceEA.lua:31-45), end-of-epoch ``synchronizeCenter`` drift repair
(mnist-ea.lua:121).  tau=10 alpha=0.2 defaults match mnist-ea.lua:18.

Run:  python examples/mnist_ea.py --numNodes 4 [--tpu]
"""

from __future__ import annotations

from common import (setup_platform, resolve_num_nodes, device_stream,
                    device_stream_stacked)
from distlearn_tpu.utils.flags import (parse_flags, CKPT_FLAGS, NODE_FLAGS,
                                       TRAIN_FLAGS, EA_FLAGS)


def main():
    opt = parse_flags("Train MNIST with elastic averaging.", {
        **NODE_FLAGS,
        **TRAIN_FLAGS,
        **EA_FLAGS,
        "learningRate": (0.01, "learning rate"),
        "data": ("", "path to .npz (default: synthetic)"),
        "numExamples": (4096, "synthetic dataset size"),
        "reportEvery": (100, "steps between reports"),
        "scanCycle": (False, "run each tau-step EASGD cycle as ONE XLA "
                             "program (build_ea_cycle) — amortizes host "
                             "dispatch on remote-attached chips"),
        "momentum": (0.0, "local heavy-ball momentum — EAMSGD "
                          "(arXiv:1412.6651 §3); 0 = plain EASGD "
                          "(the reference)"),
        **CKPT_FLAGS,
    })
    setup_platform(opt.numNodes, opt.tpu)

    import jax
    import numpy as np
    from jax import random

    from distlearn_tpu.data import (PermutationSampler, load_npz, make_dataset,
                                    synthetic_mnist)
    from distlearn_tpu.models import mnist_cnn
    from distlearn_tpu.parallel import allreduce_ea
    from distlearn_tpu.parallel.mesh import MeshTree
    from distlearn_tpu.train import (build_ea_cycle, build_ea_steps,
                                     init_ea_state, reduce_confusion)
    from distlearn_tpu.utils import checkpoint as ckpt
    from distlearn_tpu.utils import metrics as M
    from distlearn_tpu.utils.logging import root_print
    from distlearn_tpu.utils.profiling import StepTimer

    log = root_print(0)
    tree = MeshTree(num_nodes=resolve_num_nodes(opt.numNodes, opt.tpu))
    log(f"mesh: {tree.num_nodes} nodes on {jax.devices()[0].platform}")

    if opt.data:
        x, y, nc = load_npz(opt.data)
    else:
        x, y, nc = synthetic_mnist(opt.numExamples, seed=opt.seed)
    ds = make_dataset(x, y, nc)

    model = mnist_cnn()
    ets = init_ea_state(model, tree, random.PRNGKey(opt.seed), nc)
    local_step, ea_round = build_ea_steps(model, tree, lr=opt.learningRate,
                                          alpha=opt.alpha,
                                          momentum=opt.momentum)
    tau = opt.communicationTime

    start_epoch = 1
    global_step = 0
    if opt.resume and opt.save and ckpt.latest_step(opt.save) is not None:
        restorable = {"params": ets.params, "model_state": ets.model_state,
                      "center": ets.center, "vel": ets.vel}
        try:
            restored, meta = ckpt.restore_checkpoint(opt.save, restorable)
        except KeyError:
            # pre-EAMSGD checkpoint without a velocity buffer: momentum
            # restarts from zero (ets.vel is already zeros)
            restorable.pop("vel")
            restored, meta = ckpt.restore_checkpoint(opt.save, restorable)
            restored["vel"] = None
        # re-place host arrays onto the mesh (stacked per-node sharding)
        ets = ets._replace(params=tree.put_per_node(restored["params"]),
                           model_state=tree.put_per_node(
                               restored["model_state"]),
                           center=tree.put_per_node(restored["center"]),
                           vel=(tree.put_per_node(restored["vel"])
                                if restored["vel"] is not None else ets.vel))
        start_epoch = meta["step"] + 1
        # resume the step counter too: the tau-spaced elastic-round cadence
        # must continue in phase with the uninterrupted run
        global_step = int(meta.get("global_step", 0))
        log(f"resumed from epoch {meta['step']} (step {global_step})")

    cycle = (build_ea_cycle(model, tree, lr=opt.learningRate, alpha=opt.alpha,
                            momentum=opt.momentum) if opt.scanCycle else None)
    timer = StepTimer()
    last_report = global_step   # scanCycle cadence: steps since last report
    for epoch in range(start_epoch, opt.numEpochs + 1):
        sampler = PermutationSampler(ds.size, seed=opt.seed + epoch)
        if opt.scanCycle:
            # τ local steps + elastic round per dispatch; a shorter final
            # group ends the epoch with an early round (the epoch-end
            # synchronizeCenter below follows it anyway).
            timer.reset_window()   # prime: first interval starts here
            timer.tick()
            for sxs, sys_ in device_stream_stacked(tree, ds, sampler,
                                                   opt.batchSize, tau):
                k = sxs.shape[0]
                ets, losses = cycle(ets, sxs, sys_)
                timer.tick(steps=k)   # interval since last tick = this cycle
                global_step += k
                # explicit steps-since-last-report: robust to a shorter
                # final group making global_step a non-multiple of tau, and
                # to reportEvery < tau (at most one report per cycle)
                if global_step - last_report >= opt.reportEvery:
                    last_report = global_step
                    cm = reduce_confusion(ets.cm)
                    log(f"step {global_step} loss "
                        f"{float(np.mean(np.asarray(losses))):.4f} "
                        f"{M.format_confusion(cm)}")
        else:
            timer.reset_window()   # epoch-boundary scatter/ckpt not a step
            for bx, by in device_stream(tree, ds, sampler, opt.batchSize):
                timer.tick()
                ets, losses = local_step(ets, bx, by)
                global_step += 1
                if global_step % tau == 0:       # mnist-ea.lua:110 cadence
                    ets = ea_round(ets)
                if global_step % opt.reportEvery == 0:
                    cm = reduce_confusion(ets.cm)
                    log(f"step {global_step} loss "
                        f"{float(np.mean(np.asarray(losses))):.4f} "
                        f"{M.format_confusion(cm)}")
        # end-of-epoch synchronizeCenter (mnist-ea.lua:121): broadcast node
        # 0's center replica — deterministic psums keep replicas identical,
        # this is the multi-host drift repair (lua/AllReduceEA.lua:74-84)
        ets = ets._replace(
            center=tree.scatter(ets.center, src=0),
            cm=jax.tree_util.tree_map(lambda c: c * 0, ets.cm))
        log(f"epoch {epoch}: ({timer.steps_per_sec():.1f} steps/s)")
        if opt.save:
            ckpt.save_checkpoint(
                opt.save, epoch,
                {"params": ets.params, "model_state": ets.model_state,
                 "center": ets.center, "vel": ets.vel},
                metadata={"epoch": epoch, "global_step": global_step,
                          "tau": tau, "alpha": opt.alpha,
                          "momentum": opt.momentum})
    jax.block_until_ready(ets.params)
    log("done")


if __name__ == "__main__":
    main()
