#!/usr/bin/env python
"""Distributed CIFAR-10 convnet training with AllReduceSGD — the TPU-native
counterpart of examples/cifar10.lua (the reference's --cuda path becomes
--tpu; BASELINE.json north star).

Reference parity: 5-block convnet (cifar10.lua:100-163 == our
cifar_convnet), per-node batch ceil(B/N) (cifar10.lua:36), label-uniform
sampling (cifar10.lua:53-72), lr 0.1, per-epoch test pass with an allreduced
confusion matrix (cifar10.lua:210-236); checkpoint/resume added per
SURVEY.md §5.

Run:  python examples/cifar10.py --numNodes 4 --batchSize 128 [--tpu]
"""

from __future__ import annotations

from common import setup_platform, resolve_num_nodes, device_stream
from distlearn_tpu.utils.flags import (parse_flags, CKPT_FLAGS,
                                       NODE_FLAGS, TRAIN_FLAGS)


def main():
    opt = parse_flags("Train a CIFAR-10 classifier.", {
        **NODE_FLAGS,
        **TRAIN_FLAGS,
        "batchSize": (128, "global batch size"),
        "data": ("", "path to .npz with x [N,32,32,3]/y (default: synthetic)"),
        "numExamples": (8192, "synthetic dataset size"),
        "hardData": (False, "use the NON-separable synthetic set "
                     "(two-factor composition + label noise): accuracy "
                     "has a real ceiling below 1.0 instead of the "
                     "class-template set a matched filter solves"),
        "testExamples": (1024, "synthetic test-set size"),
        **CKPT_FLAGS,
        "bf16": (False, "bfloat16 compute (MXU path)"),
        "testData": ("", "path to a test-split .npz (tools/make_npz.py "
                         "emits one; default: last 10% of --data)"),
        "parity": (False, "print a final JSON accuracy line "
                          "(BASELINE.md accuracy-parity harness)"),
        "deviceData": (False, "keep the whole dataset resident in device "
                              "memory and gather batches on-device — the "
                              "TPU upgrade of torch-dataset's direct-to-GPU "
                              "cuda batcher (examples/Data.lua:27); "
                              "per-step host traffic drops to the index "
                              "vector"),
    })
    setup_platform(opt.numNodes, opt.tpu)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import random
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distlearn_tpu.data import (DeviceDataset, LabelUniformSampler,
                                    PermutationSampler, load_npz,
                                    synthetic_hard_cifar10,
                                    make_dataset, synthetic_cifar10)
    from distlearn_tpu.models import cifar_convnet
    from distlearn_tpu.parallel.mesh import MeshTree
    from distlearn_tpu.train import (build_eval_step, build_sgd_step,
                                     build_sync_step, init_train_state,
                                     reduce_confusion)
    from distlearn_tpu.utils import checkpoint as ckpt
    from distlearn_tpu.utils import metrics as M
    from distlearn_tpu.utils.logging import root_print
    from distlearn_tpu.utils.profiling import StepTimer

    log = root_print(0)
    tree = MeshTree(num_nodes=resolve_num_nodes(opt.numNodes, opt.tpu))
    log(f"mesh: {tree.num_nodes} nodes on {jax.devices()[0].platform}")

    if opt.data:
        x, y, nc = load_npz(opt.data)
        if opt.testData:
            xte, yte, _ = load_npz(opt.testData)
        else:
            n_test = max(1, len(y) // 10)
            xte, yte = x[-n_test:], y[-n_test:]
            x, y = x[:-n_test], y[:-n_test]
    else:
        synth = synthetic_hard_cifar10 if opt.hardData else synthetic_cifar10
        x, y, nc = synth(opt.numExamples, seed=opt.seed)
        xte, yte, _ = synth(opt.testExamples, seed=opt.seed + 1)
    ds = make_dataset(x, y, nc)
    ds_test = make_dataset(xte, yte, nc)

    if opt.deviceData:
        rep = NamedSharding(tree.mesh, P())
        out_sh = NamedSharding(tree.mesh, P(tree.axis_name))
        dds = DeviceDataset(ds.x, ds.y, nc, sharding=rep,
                            out_sharding=out_sh)
        dds_test = DeviceDataset(ds_test.x, ds_test.y, nc, sharding=rep,
                                 out_sharding=out_sh)

    def train_stream(sampler):
        if opt.deviceData:
            return dds.batches(sampler, opt.batchSize)
        return device_stream(tree, ds, sampler, opt.batchSize)

    def test_stream(sampler):
        if opt.deviceData:
            return dds_test.batches(sampler, opt.batchSize)
        return device_stream(tree, ds_test, sampler, opt.batchSize)

    model = cifar_convnet(
        compute_dtype=jnp.bfloat16 if opt.bf16 else None)
    ts = init_train_state(model, tree, random.PRNGKey(opt.seed), nc)
    step = build_sgd_step(model, tree, lr=opt.learningRate)
    sync = build_sync_step(tree)
    ev = build_eval_step(model, tree)

    start_epoch = 1
    if opt.resume and opt.save and ckpt.latest_step(opt.save) is not None:
        restorable = {"params": ts.params, "model_state": ts.model_state}
        restored, meta = ckpt.restore_checkpoint(opt.save, restorable)
        ts = ts._replace(params=restored["params"],
                         model_state=restored["model_state"])
        start_epoch = meta["step"] + 1
        log(f"resumed from epoch {meta['step']}")

    timer = StepTimer()
    # pre-bind report state: --parity must emit a line even for a zero-epoch
    # run (e.g. --resume after training already completed)
    train_cm = reduce_confusion(ts.cm)
    cm = jnp.zeros_like(ts.cm)
    for epoch in range(start_epoch, opt.numEpochs + 1):
        sampler = LabelUniformSampler(ds.y, seed=opt.seed + epoch)
        timer.reset_window()   # epoch-boundary eval/ckpt time is not a step
        for bx, by in train_stream(sampler):
            timer.tick()
            ts, loss = step(ts, bx, by)
        ts = sync(ts)
        train_cm = reduce_confusion(ts.cm)
        ts = ts._replace(cm=jax.tree_util.tree_map(lambda c: c * 0, ts.cm))

        # per-epoch test pass with allreduced confusion (cifar10.lua:210-236)
        cm = jax.device_put(
            jnp.zeros((tree.num_nodes, nc, nc), jnp.int32),
            NamedSharding(tree.mesh, P(tree.axis_name)))
        tsampler = PermutationSampler(ds_test.size, seed=0)
        for bx, by in test_stream(tsampler):
            cm, test_loss = ev(ts.params, ts.model_state, cm, bx, by)
        log(f"epoch {epoch}: train {M.format_confusion(train_cm)} | "
            f"test {M.format_confusion(reduce_confusion(cm))} "
            f"({timer.steps_per_sec():.2f} steps/s)")

        if opt.save:
            ckpt.save_checkpoint(
                opt.save, epoch,
                {"params": ts.params, "model_state": ts.model_state},
                metadata={"epoch": epoch})
    jax.block_until_ready(ts.params)
    if opt.parity:
        # One machine-readable line for the parity table (docs/PARITY.md).
        import json
        print(json.dumps({
            "example": "cifar10", "epochs": opt.numEpochs,
            "data": "npz" if opt.data else "synthetic",
            "global_batch": opt.batchSize, "nodes": tree.num_nodes,
            "train_acc": round(M.total_valid(train_cm), 4),
            "test_acc": round(M.total_valid(reduce_confusion(cm)), 4),
        }))
    log("done")


if __name__ == "__main__":
    main()
