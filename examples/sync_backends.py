#!/usr/bin/env python
"""One EASGD loop, three collective backends — the ISSUE 20 demo.

The same :class:`~distlearn_tpu.parallel.allreduce_ea.AllReduceEA`
driver runs over every :class:`~distlearn_tpu.comm.backend` topology:

* ``--backend mesh``   — all N nodes are devices in one SPMD mesh
  (the fused in-process fast path).
* ``--backend host``   — every node its own TCP tree rank (the
  reference torch-ipc topology; here localhost threads).
* ``--backend hybrid`` — N nodes split over ``--numHosts`` host ranks,
  each fronting N/numHosts device-nodes: in-mesh reduce-scatter, ONE
  TCP leg per host, in-mesh all-gather.

With dyadic-exact arithmetic (dyadic f64 params, dyadic alpha whose
center recursion ``|1 - N*alpha|`` stays contractive, so magnitudes
never outgrow the 53-bit mantissa)
the three trajectories are BITWISE identical — the printed digest is
the same line for every ``--backend`` — while the hybrid host leg
moves ~numNodes/numHosts-fold fewer TCP bytes than the flat host tree
(tests/test_backend.py asserts both properties; bench.py
``host_sync_bench`` measures the byte ratio).

Run:  python examples/sync_backends.py --backend mesh --numNodes 8
      python examples/sync_backends.py --backend host --numNodes 8
      python examples/sync_backends.py --backend hybrid --numNodes 8 \
          --numHosts 2
"""

from __future__ import annotations

import hashlib

from common import setup_platform
from distlearn_tpu.utils.flags import parse_flags


def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _node_step(params, rank, r):
    """One deterministic dyadic 'gradient' step — stands in for a real
    per-node training step, exact in f64 so reduction order can't show."""
    import numpy as np
    g = (np.arange(params.size, dtype=np.float64).reshape(params.shape)
         % 7 + rank + r) * 0.25
    return params - 0.5 * g


def _run_rank(backend, rank, local, rounds, tau, alpha, dim):
    """Drive ``local`` logical nodes' EASGD over one backend handle.

    Plain HostBackend handles see one node (``local == 1``, plain
    arrays); mesh/hybrid handles see a stacked ``[local, dim]`` slice."""
    import numpy as np

    from distlearn_tpu.parallel.allreduce_ea import AllReduceEA

    ea = AllReduceEA(backend, tau, alpha)
    lo = backend.node_offset
    if getattr(backend, "stacked_nodes", None) is None:
        params = np.zeros(dim, np.float64)
        for r in range(rounds):
            params = _node_step(params, lo, r)
            params = ea.average_parameters(params)
    else:
        params = np.zeros((local, dim), np.float64)
        for r in range(rounds):
            params = np.stack([_node_step(params[i], lo + i, r)
                               for i in range(local)])
            params = ea.average_parameters(params)
    return np.asarray(ea._center), np.asarray(params)


def main():
    opt = parse_flags(
        "EASGD over the topology-aware collective backends.", {
            "backend": ("mesh", "mesh | host | hybrid"),
            "numNodes": (8, "logical nodes"),
            "numHosts": (2, "host ranks (hybrid only)"),
            "rounds": (20, "elastic rounds"),
            "tau": (1, "steps between averaging rounds"),
            "alpha": (0.0625, "elastic moving rate (dyadic AND "
                              "contractive at N nodes => bitwise "
                              "across backends)"),
            "dim": (64, "parameter vector length"),
            "tpu": (False, "run on the TPU backend"),
        })
    setup_platform(opt.numNodes, opt.tpu)

    import jax
    import numpy as np

    # integer-valued f64 + dyadic alpha is the bitwise-parity contract;
    # without x64 the mesh/hybrid paths would silently round in f32
    jax.config.update("jax_enable_x64", True)

    from distlearn_tpu.comm.backend import (HostBackend, HybridBackend,
                                            MeshBackend)

    n, rounds = opt.numNodes, opt.rounds
    if opt.backend == "mesh":
        b = MeshBackend(num_nodes=n)
        center, _ = _run_rank(b, 0, n, rounds, opt.tau, opt.alpha, opt.dim)
        center = b.node_slice(center, 0) if center.ndim > 1 else center

    elif opt.backend == "host":
        from distlearn_tpu.comm.tree import tree_map_spawn
        port = _free_port()

        def node(rank):
            b = HostBackend.create(rank, n, "127.0.0.1", port, base=2)
            out = _run_rank(b, rank, 1, rounds, opt.tau, opt.alpha,
                            opt.dim)
            b.close()
            return out
        center = tree_map_spawn(node, n, timeout=300)[0][0]

    elif opt.backend == "hybrid":
        from distlearn_tpu.comm.tree import tree_map_spawn
        hosts = opt.numHosts
        if n % hosts:
            raise SystemExit(f"--numNodes {n} not divisible by "
                             f"--numHosts {hosts}")
        local = n // hosts
        devs = jax.devices()
        port = _free_port()

        def node(rank):
            # disjoint device slices: each host rank's in-mesh
            # collectives rendezvous only within its own slice
            b = HybridBackend(rank, hosts, "127.0.0.1", port,
                              devices=devs[rank * local:(rank + 1) * local])
            out = _run_rank(b, rank, local, rounds, opt.tau, opt.alpha,
                            opt.dim)
            b.close()
            return out
        res = tree_map_spawn(node, hosts, timeout=300)
        center = res[0][0]
        center = center[0] if center.ndim > 1 else center

    else:
        raise SystemExit(f"unknown --backend {opt.backend!r}")

    center = np.asarray(center, np.float64).reshape(-1)
    digest = hashlib.sha256(center.tobytes()).hexdigest()[:16]
    print(f"backend={opt.backend} nodes={n} rounds={rounds} "
          f"center[0:4]={center[:4].tolist()} digest={digest}")


if __name__ == "__main__":
    main()
