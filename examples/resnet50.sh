#!/usr/bin/env bash
# ResNet-50 v1.5 stretch config (BASELINE.md row 5).  One SPMD process
# drives all nodes (vs the reference's process-per-node .sh pattern).
# CPU smoke: tiny images + capped steps so it finishes in minutes.
set -e
cd "$(dirname "$0")"
python resnet50.py --numNodes 8 --batchSize 64 --imageSize 64 \
  --numExamples 256 --numClasses 100 --numEpochs 1 --stepsPerEpoch 4 "$@"
