#!/usr/bin/env python
"""Request driver for a ``examples/lm.py --serve`` endpoint.

Dials the serving port, submits one or more generate requests over the
'G'/'R' framed protocol (docs/SERVING.md), streams tokens as they
arrive, and reports per-request time-to-first-token and aggregate
throughput.  ``--concurrency N`` opens N connections and submits in
parallel — the server's continuous batching packs them into one decode
tick, so aggregate tok/s should scale well past a single request's.

    python examples/lm.py --dp 1 --sp 1 --tp 1 --steps 5 \
        --serve 4 --servePort 9123 &
    python examples/lm_client.py --port 9123 --concurrency 4
"""

from __future__ import annotations

import sys
import threading
import time

import common  # noqa: F401 — sys.path bootstrap for distlearn_tpu
from distlearn_tpu.utils.flags import parse_flags


def main():
    opt = parse_flags("Drive a distlearn_tpu serving endpoint.", {
        "host": ("127.0.0.1", "serving host"),
        "port": (0, "serving port (required; printed by lm.py --serve)"),
        "prompt": ("", "comma-separated token ids (empty = a fixed "
                       "8-token demo prompt)"),
        "maxNew": (16, "tokens to generate per request"),
        "concurrency": (1, "parallel requests, one connection each"),
        "deadline": (0.0, "per-request deadline seconds (0 = none; the "
                          "server evicts requests that exceed it)"),
        "ping": (False, "just print the server's health snapshot and exit"),
    })
    if not opt.port:
        raise SystemExit("--port is required (lm.py --serve prints it)")
    from distlearn_tpu.serve import ServeClient

    if opt.ping:
        with ServeClient(opt.host, opt.port) as c:
            print(c.ping())
        return

    if opt.prompt:
        prompt = [int(t) for t in opt.prompt.split(",")]
    else:
        prompt = [1, 7, 3, 9, 2, 8, 4, 6]

    results: dict[int, dict] = {}
    t0 = time.perf_counter()

    def run(i: int):
        with ServeClient(opt.host, opt.port) as c:
            ts = time.perf_counter()
            ttft = [None]

            def on_chunk(_toks, _t=ts):
                if ttft[0] is None:
                    ttft[0] = time.perf_counter() - _t
            r = c.generate(prompt, opt.maxNew, rid=f"req{i}",
                           deadline_s=opt.deadline or None,
                           on_chunk=on_chunk)
            results[i] = {"tokens": r["tokens"], "ttft": ttft[0],
                          "reason": r["reason"]}

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(opt.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    total = 0
    for i in sorted(results):
        r = results[i]
        total += len(r["tokens"])
        print(f"req{i}: {len(r['tokens'])} tokens "
              f"(ttft {r['ttft'] * 1e3:.1f}ms, {r['reason']}): "
              f"{r['tokens']}")
    if len(results) < opt.concurrency:
        print(f"{opt.concurrency - len(results)} request(s) failed",
              file=sys.stderr)
        sys.exit(1)
    print(f"{total} tokens over {len(results)} request(s) in "
          f"{wall:.2f}s = {total / wall:.1f} tok/s aggregate")


if __name__ == "__main__":
    main()
