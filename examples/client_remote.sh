#!/bin/bash
# Multi-host topology on one machine: two PROCESSES (the reference's
# process-per-host shape, client_remote.sh) training over the TCP tree and
# ending with bitwise-identical params (compare the printed digests).
# For real multi-host runs see the flags in client_remote.py's docstring.
cd "$(dirname "$0")"
PORT=${PORT:-9090}
N=${N:-2}
for i in $(seq 2 $N); do
  python client_remote.py --nodeIndex "$i" --numNodes "$N" --port "$PORT" \
    --numEpochs 2 "$@" &
done
python client_remote.py --nodeIndex 1 --numNodes "$N" --port "$PORT" \
  --numEpochs 2 "$@"
wait
