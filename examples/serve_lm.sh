#!/bin/bash
# One-command serving demo (the asyncEASGD.sh pattern for inference):
# train a small LM for a few steps, serve it with continuous batching,
# fire CONCURRENCY parallel requests at it, then SIGTERM the server and
# let the drain finish the in-flight requests.
#   PORT=9123 CONCURRENCY=8 ./serve_lm.sh
cd "$(dirname "$0")"
PORT=${PORT:-9123}
SLOTS=${SLOTS:-4}
CONCURRENCY=${CONCURRENCY:-4}
STEPS=${STEPS:-5}
MAXNEW=${MAXNEW:-16}

python lm.py --dp 1 --sp 1 --tp 1 --steps "$STEPS" \
  --serve "$SLOTS" --servePort "$PORT" &
SERVER=$!
trap 'kill $SERVER 2>/dev/null' EXIT

python lm_client.py --port "$PORT" --concurrency "$CONCURRENCY" \
  --maxNew "$MAXNEW"
RC=$?

kill -TERM $SERVER 2>/dev/null   # graceful drain (ha.install_signal_flush)
wait $SERVER
exit $RC
