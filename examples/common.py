"""Shared example plumbing (the reference's examples share Data.lua/Model.lua;
here: platform setup + data/stream helpers).

One SPMD process drives ALL nodes: where the reference launches N OS
processes connected by TCP (examples/mnist.sh spawning ``th mnist.lua
--nodeIndex i &``), a JAX program places one program over an N-device mesh.
``--numNodes`` picks the mesh size; ``--nodeIndex`` is accepted for CLI
parity and used only to label multi-host processes.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup_platform(num_nodes: int, tpu: bool):
    """Pick the backend BEFORE any device query.

    --tpu: use the real TPU backend (devices as-is).  Otherwise: CPU with
    ``num_nodes`` virtual host devices (the reference's LocalhostTree
    analogue, SURVEY.md §4).
    """
    if tpu:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={num_nodes}"
        ).strip()
    import jax
    jax.config.update("jax_platforms", "cpu")


def data_sharding(tree):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(tree.mesh, P(tree.axis_name))


def device_stream(tree, ds, sampler, batch, prefetch=2):
    from distlearn_tpu.data import batch_iterator, prefetch_to_device
    sh = data_sharding(tree)
    return prefetch_to_device(batch_iterator(ds, sampler, batch),
                              size=prefetch, sharding=sh)
