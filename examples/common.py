"""Shared example plumbing (the reference's examples share Data.lua/Model.lua;
here: platform setup + data/stream helpers).

One SPMD process drives ALL nodes: where the reference launches N OS
processes connected by TCP (examples/mnist.sh spawning ``th mnist.lua
--nodeIndex i &``), a JAX program places one program over an N-device mesh.
``--numNodes`` picks the mesh size; ``--nodeIndex`` is accepted for CLI
parity and used only to label multi-host processes.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup_platform(num_nodes: int, tpu: bool):
    """Pick the backend BEFORE any device query.

    --tpu: use the real TPU backend (devices as-is).  Otherwise: CPU with
    ``num_nodes`` virtual host devices (the reference's LocalhostTree
    analogue, SURVEY.md §4).
    """
    from distlearn_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache()   # DISTLEARN_TPU_COMPILE_CACHE warm starts
    if tpu:
        return
    from distlearn_tpu.utils.platform import force_cpu
    force_cpu(num_nodes)


def resolve_num_nodes(requested: int, tpu: bool) -> int:
    """Clamp ``--numNodes`` to what the attached backend offers.

    The reference oversubscribes by time-slicing N processes on one GPU
    (examples/cifar10-cuda.sh); an SPMD mesh has exactly one program per
    device, so on a 1-chip TPU a 4-node request becomes a 1-node run with a
    loud warning instead of a crash (VERDICT r1 weak #5).  On CPU the
    requested count is virtualized by :func:`setup_platform`, so it always
    fits.
    """
    if not tpu:
        return requested
    import sys

    import jax
    avail = len(jax.devices())
    if requested > avail:
        print(f"[distlearn_tpu] --numNodes {requested} exceeds the "
              f"{avail} attached TPU chip(s); running {avail} node(s). "
              "(The reference time-slices processes per GPU; an SPMD mesh "
              "needs one device per node.)", file=sys.stderr)
        return avail
    return requested


def data_sharding(tree):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(tree.mesh, P(tree.axis_name))


def device_stream(tree, ds, sampler, batch, prefetch=2):
    from distlearn_tpu.data import batch_iterator, prefetch_to_device
    sh = data_sharding(tree)
    return prefetch_to_device(batch_iterator(ds, sampler, batch),
                              size=prefetch, sharding=sh)


def device_stream_stacked(tree, ds, sampler, batch, k, prefetch=2):
    """Group ``k`` consecutive batches into one ``[k, B, ...]`` super-batch
    for the scanned trainers (``train.build_sgd_scan_step`` /
    ``train.build_ea_cycle``): the step axis is replicated, the batch axis
    sharded over the mesh.  A shorter final group is yielded as-is (the scan
    reads its length from the shape; one extra compile per distinct length).
    """
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distlearn_tpu.data import batch_iterator, prefetch_to_device
    sh = NamedSharding(tree.mesh, P(None, tree.axis_name))

    def groups():
        xs, ys = [], []
        for bx, by in batch_iterator(ds, sampler, batch):
            xs.append(bx)
            ys.append(by)
            if len(xs) == k:
                yield np.stack(xs), np.stack(ys)
                xs, ys = [], []
        if xs:
            yield np.stack(xs), np.stack(ys)
    return prefetch_to_device(groups(), size=prefetch, sharding=sh)
