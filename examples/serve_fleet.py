#!/usr/bin/env python
"""Fault-tolerant serving fleet demo (docs/SERVING.md fleet section).

One process, whole story: spin ``--replicas`` ServeServer replicas over
a shared tiny LM, put a :class:`distlearn_tpu.serve.Router` in front,
and drive traffic through three acts:

1. **Steady state** — least-loaded dispatch spreads requests across the
   fleet; every stream completes.
2. **Replica kill** — one replica dies mid-traffic.  Requests it held
   but had not prefilled are resubmitted to survivors by the router;
   the fleet keeps serving.
3. **Hot weight swap** — a new checkpoint lands in the tailed directory
   with a bumped ``epoch``; every replica swaps between decode ticks
   and the router's epoch fence guarantees no stream mixes weights.

    python examples/serve_fleet.py --replicas 3 --requests 12
"""

from __future__ import annotations

import shutil
import tempfile
import threading
import time

import common  # noqa: F401 — sys.path bootstrap for distlearn_tpu
from distlearn_tpu.utils.flags import parse_flags

VOCAB, DIM, DEPTH, HEADS, MAX_LEN = 61, 32, 2, 4, 64


def build_fleet(n, params, ckpt_dir, epoch=1):
    from distlearn_tpu.serve import DecodeEngine, ServeServer
    servers = []
    for _ in range(n):
        eng = DecodeEngine(params, num_slots=2, max_len=MAX_LEN, page=8)
        srv = ServeServer(eng, idle_wait=0.005, ckpt_dir=ckpt_dir,
                          ckpt_poll=0.05, epoch=epoch)
        srv.start()
        servers.append(srv)
    return servers


def fire(router, prompts, max_new, kill_at=None, kill=None):
    """Drive one request per prompt through the router concurrently.
    ``kill`` (a thunk) runs once the ``kill_at``-th request is submitted
    — the mid-traffic fault."""
    results = [None] * len(prompts)

    def one(i):
        if kill_at is not None and i == kill_at:
            kill()
        try:
            results[i] = router.generate(prompts[i], max_new,
                                         rid=f"req{i}", timeout=120)
        except Exception as e:  # noqa: BLE001 — demo: report, don't die
            results[i] = {"reason": f"error: {e}", "tokens": [],
                          "epoch": None, "replica": None}

    threads = []
    for i in range(len(prompts)):
        t = threading.Thread(target=one, args=(i,))
        t.start()
        threads.append(t)
        time.sleep(0.02)         # stagger so the fleet sees a stream
    for t in threads:
        t.join()
    return results


def report(act, results):
    done = sum(1 for r in results if r["reason"] == "complete")
    by_rep: dict = {}
    for r in results:
        if r["replica"]:
            by_rep[r["replica"]] = by_rep.get(r["replica"], 0) + 1
    epochs = sorted({r["epoch"] for r in results if r["epoch"]})
    print(f"[{act}] {done}/{len(results)} complete; "
          f"dispatch={by_rep}; epochs={epochs}")
    return done


def main():
    opt = parse_flags("Fault-tolerant serving fleet demo.", {
        "replicas": (3, "fleet size"),
        "requests": (12, "requests per act"),
        "maxNew": (12, "tokens to generate per request"),
        "seed": (0, "prompt RNG seed"),
    })
    import jax
    import numpy as np

    from distlearn_tpu.models.transformer import transformer_lm
    from distlearn_tpu.serve import Router
    from distlearn_tpu.utils.checkpoint import save_checkpoint

    model = transformer_lm(vocab=VOCAB, dim=DIM, depth=DEPTH, heads=HEADS,
                           max_len=MAX_LEN)
    params, _ = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(opt.seed)

    def prompts(n):
        return [rng.integers(1, VOCAB, size=rng.integers(3, 9))
                .astype(np.int32) for _ in range(n)]

    ckpt_dir = tempfile.mkdtemp(prefix="serve_fleet_")
    servers = build_fleet(opt.replicas, params, ckpt_dir)
    router = Router([(s.host, s.port) for s in servers], health_ttl=0.05,
                    retry_interval=0.02)
    try:
        print(f"fleet up: {opt.replicas} replicas at "
              + ", ".join(f"{s.host}:{s.port}" for s in servers))

        # act 1: steady state
        report("steady", fire(router, prompts(opt.requests), opt.maxNew))

        # act 2: kill one replica mid-traffic; router resubmits its
        # queued-not-prefilled requests to survivors
        victim = servers[0]
        res = fire(router, prompts(opt.requests), opt.maxNew,
                   kill_at=opt.requests // 2, kill=victim.stop)
        report("kill 1 replica", res)

        # act 3: hot swap — land a new checkpoint at epoch 2; survivors
        # tail it, swap between ticks, and echo the new epoch
        new_params = jax.tree_util.tree_map(lambda a: a * 0.5, params)
        save_checkpoint(ckpt_dir, 1, new_params, metadata={"epoch": 2})
        deadline = time.monotonic() + 30
        while any(s.epoch != 2 for s in servers[1:]):
            if time.monotonic() > deadline:
                raise SystemExit("hot swap never landed")
            time.sleep(0.05)
        res = fire(router, prompts(opt.requests), opt.maxNew)
        report("post hot-swap", res)
        assert {r["epoch"] for r in res} == {2}, "epoch fence violated"
        print("done: fleet survived a kill and an epoch-fenced hot swap")
    finally:
        router.close()
        for s in servers:
            s.stop()
        shutil.rmtree(ckpt_dir, ignore_errors=True)


if __name__ == "__main__":
    main()
