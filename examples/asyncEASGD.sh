#!/bin/bash
# Counterpart of examples/AsyncEASGD.sh: parameter server + tester + 2
# worker clients on localhost.  The reference kills stale ports with fuser
# and derives the server IP from ifconfig; localhost + fresh port suffices
# here (multi-host: pass --host/--port to each role).
cd "$(dirname "$0")"
PORT=${PORT:-9500}
NODES=2
EPOCHS=${EPOCHS:-1}
BATCH=${BATCH:-16}
N=${N:-256}
MODEL=${MODEL:-mnist}
TAU=${TAU:-4}
# steps/epoch = (N/NODES)/BATCH; syncs = NODES*EPOCHS*(steps/tau)
STEPS_PER_EPOCH=$(( (N / NODES) / BATCH ))
# client sync counters run continuously across epochs
SYNCS=$(( NODES * ((EPOCHS * STEPS_PER_EPOCH) / TAU) ))
TESTTIME=${TESTTIME:-4}
NUMTESTS=$(( SYNCS / TESTTIME + 1 ))

common="--numNodes $NODES --port $PORT --numEpochs $EPOCHS --batchSize $BATCH \
  --numExamples $N --communicationTime $TAU --model $MODEL"
# CONCURRENT=1 serves clients on overlapped worker threads
# (AsyncEAServerConcurrent) instead of the reference's critical section
SERVER_FLAGS=${CONCURRENT:+--concurrent}
# SHARDS=N stripes the center across N shard channels (docs/PERF.md);
# clients negotiate the plan in the Enter? handshake automatically
SERVER_FLAGS="$SERVER_FLAGS ${SHARDS:+--shards $SHARDS}"

python easgd_server.py $common --tester --testTime $TESTTIME --numSyncs $SYNCS $SERVER_FLAGS &
SERVER=$!
python easgd_tester.py $common --numTests $NUMTESTS &
TESTER=$!
python easgd_client.py $common --nodeIndex 1 --verbose &
C1=$!
python easgd_client.py $common --nodeIndex 2 --verbose &
C2=$!
wait $SERVER $TESTER $C1 $C2
