#!/bin/bash
# Counterpart of examples/AsyncEASGD.sh: parameter server + tester + 2
# worker clients on localhost.  The reference kills stale ports with fuser
# and derives the server IP from ifconfig; localhost + fresh port suffices
# here (multi-host: pass --host/--port to each role).
cd "$(dirname "$0")"
PORT=${PORT:-9500}
NODES=2
EPOCHS=${EPOCHS:-1}
BATCH=${BATCH:-16}
N=${N:-256}
MODEL=${MODEL:-mnist}
TAU=${TAU:-4}
# steps/epoch = (N/NODES)/BATCH; syncs = NODES*EPOCHS*(steps/tau)
STEPS_PER_EPOCH=$(( (N / NODES) / BATCH ))
# client sync counters run continuously across epochs
SYNCS=$(( NODES * ((EPOCHS * STEPS_PER_EPOCH) / TAU) ))
TESTTIME=${TESTTIME:-4}
NUMTESTS=$(( SYNCS / TESTTIME + 1 ))

common="--numNodes $NODES --port $PORT --numEpochs $EPOCHS --batchSize $BATCH \
  --numExamples $N --communicationTime $TAU --model $MODEL"
# CONCURRENT=1 serves clients on overlapped worker threads
# (AsyncEAServerConcurrent) instead of the reference's critical section
SERVER_FLAGS=${CONCURRENT:+--concurrent}
# SHARDS=N stripes the center across N shard channels (docs/PERF.md);
# clients negotiate the plan in the Enter? handshake automatically
SERVER_FLAGS="$SERVER_FLAGS ${SHARDS:+--shards $SHARDS}"
# CENTER_CKPT=dir turns on HA checkpointing of the center (+ one final
# flush on SIGTERM); CKPT_EVERY tunes the cadence.  STANDBY_PORT=p also
# launches a warm standby on that port tailing the same directory and
# points the clients' failover dial list at it (docs/HA.md).
SERVER_FLAGS="$SERVER_FLAGS ${CENTER_CKPT:+--centerCkpt $CENTER_CKPT}"
SERVER_FLAGS="$SERVER_FLAGS ${CKPT_EVERY:+--ckptEvery $CKPT_EVERY}"
CLIENT_FLAGS=${STANDBY_PORT:+--centers 127.0.0.1:$STANDBY_PORT}

python easgd_server.py $common --tester --testTime $TESTTIME --numSyncs $SYNCS $SERVER_FLAGS &
SERVER=$!
STANDBY=
if [ -n "$STANDBY_PORT" ] && [ -n "$CENTER_CKPT" ]; then
  # the standby binds its own port window now, promotes only when the
  # primary's checkpoints appear AND the fleet re-dials it
  python easgd_server.py $common --port $STANDBY_PORT --concurrent --standby \
    --watchPrimary 127.0.0.1:$PORT --syncTimeout 15 \
    --numSyncs $SYNCS $SERVER_FLAGS &
  STANDBY=$!
fi
# KILL_AFTER_CKPTS=n SIGTERMs the primary once n checkpoints are on disk
# (i.e. provably mid-serving with restorable state): the failover drill
# from docs/HA.md — final flush, standby promotes, clients re-dial it
if [ -n "$KILL_AFTER_CKPTS" ] && [ -n "$CENTER_CKPT" ]; then
  (
    while [ "$(ls "$CENTER_CKPT" 2>/dev/null | wc -l)" -lt "$KILL_AFTER_CKPTS" ]; do
      sleep 0.2
    done
    echo "[chaos] $KILL_AFTER_CKPTS checkpoints on disk; SIGTERM primary $SERVER"
    kill -TERM $SERVER
  ) &
fi
python easgd_tester.py $common --numTests $NUMTESTS &
TESTER=$!
python easgd_client.py $common --nodeIndex 1 --verbose $CLIENT_FLAGS &
C1=$!
python easgd_client.py $common --nodeIndex 2 --verbose $CLIENT_FLAGS &
C2=$!
wait $SERVER $TESTER $C1 $C2 $STANDBY
