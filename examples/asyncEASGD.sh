#!/bin/bash
# Counterpart of examples/AsyncEASGD.sh: parameter server + tester + 2
# worker clients on localhost.  The reference kills stale ports with fuser
# and derives the server IP from ifconfig; localhost + fresh port suffices
# here (multi-host: pass --host/--port to each role).
cd "$(dirname "$0")"
# --join-after S / --leave-after S: elastic membership drills
# (docs/ELASTIC.md).  Either flag switches the server to
# --concurrent --elastic; the joiner enters mid-run as client 3 through
# the Join? handshake, the leaver is client 2 departing gracefully via
# Leave? (pending delta flushed through the ledger, not dropped).
JOIN_AFTER=${JOIN_AFTER:-}
LEAVE_AFTER=${LEAVE_AFTER:-}
while [ $# -gt 0 ]; do
  case "$1" in
    --join-after)  JOIN_AFTER=$2; shift 2 ;;
    --leave-after) LEAVE_AFTER=$2; shift 2 ;;
    *) echo "usage: $0 [--join-after SECS] [--leave-after SECS]" >&2; exit 2 ;;
  esac
done
PORT=${PORT:-9500}
NODES=2
EPOCHS=${EPOCHS:-1}
BATCH=${BATCH:-16}
N=${N:-256}
MODEL=${MODEL:-mnist}
TAU=${TAU:-4}
# steps/epoch = (N/NODES)/BATCH; syncs = NODES*EPOCHS*(steps/tau)
STEPS_PER_EPOCH=$(( (N / NODES) / BATCH ))
# client sync counters run continuously across epochs
SYNCS=$(( NODES * ((EPOCHS * STEPS_PER_EPOCH) / TAU) ))
TESTTIME=${TESTTIME:-4}
NUMTESTS=$(( SYNCS / TESTTIME + 1 ))

common="--numNodes $NODES --port $PORT --numEpochs $EPOCHS --batchSize $BATCH \
  --numExamples $N --communicationTime $TAU --model $MODEL"
# CONCURRENT=1 serves clients on overlapped worker threads
# (AsyncEAServerConcurrent) instead of the reference's critical section
ELASTIC=
if [ -n "$JOIN_AFTER$LEAVE_AFTER" ]; then
  CONCURRENT=1   # elastic membership needs the concurrent server
  ELASTIC=1
fi
SERVER_FLAGS=${CONCURRENT:+--concurrent}
SERVER_FLAGS="$SERVER_FLAGS ${ELASTIC:+--elastic}"
# SHARDS=N stripes the center across N shard channels (docs/PERF.md);
# clients negotiate the plan in the Enter? handshake automatically
SERVER_FLAGS="$SERVER_FLAGS ${SHARDS:+--shards $SHARDS}"
# CENTER_CKPT=dir turns on HA checkpointing of the center (+ one final
# flush on SIGTERM); CKPT_EVERY tunes the cadence.  STANDBY_PORT=p also
# launches a warm standby on that port tailing the same directory and
# points the clients' failover dial list at it (docs/HA.md).
SERVER_FLAGS="$SERVER_FLAGS ${CENTER_CKPT:+--centerCkpt $CENTER_CKPT}"
SERVER_FLAGS="$SERVER_FLAGS ${CKPT_EVERY:+--ckptEvery $CKPT_EVERY}"
CLIENT_FLAGS=${STANDBY_PORT:+--centers 127.0.0.1:$STANDBY_PORT}

# Membership drills make the served-sync count dynamic (a leaver serves
# fewer, a joiner more), so the tester's fixed push cadence cannot be
# precomputed: skip the eval channel, give the sync budget slack, and
# let the server stop when the fleet drains (or goes idle).
if [ -n "$ELASTIC" ]; then
  SYNCS=$(( SYNCS * 3 ))
  python easgd_server.py $common --numSyncs $SYNCS --syncTimeout 30 $SERVER_FLAGS &
else
  python easgd_server.py $common --tester --testTime $TESTTIME --numSyncs $SYNCS $SERVER_FLAGS &
fi
SERVER=$!
STANDBY=
if [ -n "$STANDBY_PORT" ] && [ -n "$CENTER_CKPT" ]; then
  # the standby binds its own port window now, promotes only when the
  # primary's checkpoints appear AND the fleet re-dials it
  python easgd_server.py $common --port $STANDBY_PORT --concurrent --standby \
    --watchPrimary 127.0.0.1:$PORT --syncTimeout 15 \
    --numSyncs $SYNCS $SERVER_FLAGS &
  STANDBY=$!
fi
# KILL_AFTER_CKPTS=n SIGTERMs the primary once n checkpoints are on disk
# (i.e. provably mid-serving with restorable state): the failover drill
# from docs/HA.md — final flush, standby promotes, clients re-dial it
if [ -n "$KILL_AFTER_CKPTS" ] && [ -n "$CENTER_CKPT" ]; then
  (
    while [ "$(ls "$CENTER_CKPT" 2>/dev/null | wc -l)" -lt "$KILL_AFTER_CKPTS" ]; do
      sleep 0.2
    done
    echo "[chaos] $KILL_AFTER_CKPTS checkpoints on disk; SIGTERM primary $SERVER"
    kill -TERM $SERVER
  ) &
fi
TESTER=
if [ -z "$ELASTIC" ]; then
  python easgd_tester.py $common --numTests $NUMTESTS &
  TESTER=$!
fi
python easgd_client.py $common --nodeIndex 1 --verbose $CLIENT_FLAGS &
C1=$!
# the leave drill rides client 2: it trains, announces Leave? after the
# deadline (flushing its in-flight delta), and exits cleanly
python easgd_client.py $common --nodeIndex 2 --verbose $CLIENT_FLAGS \
  ${LEAVE_AFTER:+--leaveAfter $LEAVE_AFTER} &
C2=$!
C3=
if [ -n "$JOIN_AFTER" ]; then
  # the join drill: a third client enters the running fleet via Join? —
  # the server assigns its cid and streams the live center before it
  # counts as a member (the join fence)
  ( sleep "$JOIN_AFTER"
    echo "[drill] client 3 joining the fleet after ${JOIN_AFTER}s"
    exec python easgd_client.py $common --nodeIndex 3 --joinFleet \
      --verbose $CLIENT_FLAGS ) &
  C3=$!
fi
wait $SERVER $TESTER $C1 $C2 $C3 $STANDBY
