#!/bin/bash
# Reference parity: examples/mnist-ea.sh (4 nodes, elastic averaging).
cd "$(dirname "$0")"
python mnist_ea.py --numNodes 4 --numEpochs 4 "$@"
