#!/bin/bash
# Long-context LM on an 8-device virtual mesh: dp2 x sp2(ring attn) x tp2,
# a 4-expert MoE variant with the Switch balance loss (experts sharded
# over the data axis), a dp2 x pipe4 GPipe pipeline (2 blocks per stage,
# remat), the same pipeline under the 1F1B schedule (O(stages) activation
# liveness), ZeRO-1 Adam with sharded f32 masters composed with sp/tp,
# the zigzag causal ring layout (masked attention blocks never
# computed) with selective remat, and mixed precision (bf16 working
# params + f32 masters) on the full 3D mesh.
cd "$(dirname "$0")"
python lm.py --dp 2 --sp 2 --tp 2 "$@"
python lm.py --dp 4 --sp 2 --tp 1 --moeExperts 4 --moeBalanceWeight 0.01 "$@"
python lm.py --dp 2 --sp 1 --tp 1 --pp 4 --depth 8 --remat "$@"
python lm.py --dp 2 --sp 1 --tp 1 --pp 4 --depth 8 --ppSchedule 1f1b "$@"
python lm.py --dp 2 --sp 2 --tp 2 --zero --learningRate 0.003 "$@"
python lm.py --dp 2 --sp 4 --tp 1 --seqLayout zigzag --rematMode mlp "$@"
python lm.py --dp 2 --sp 2 --tp 2 --mixed "$@"
python lm.py --dp 8 --sp 1 --tp 1 --fsdp "$@"
