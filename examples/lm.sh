#!/bin/bash
# Long-context LM on an 8-device virtual mesh: dp2 x sp2(ring attn) x tp2,
# a 4-expert MoE variant (experts sharded over the data axis), and a
# dp2 x pipe4 GPipe pipeline (one block per stage).
cd "$(dirname "$0")"
python lm.py --dp 2 --sp 2 --tp 2 "$@"
python lm.py --dp 4 --sp 2 --tp 1 --moeExperts 4 "$@"
python lm.py --dp 2 --sp 1 --tp 1 --pp 4 --depth 4 "$@"
