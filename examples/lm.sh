#!/bin/bash
# Long-context LM on an 8-device virtual mesh: dp2 x sp2(ring attn) x tp2,
# then a 4-expert MoE variant with experts sharded over the data axis.
cd "$(dirname "$0")"
python lm.py --dp 2 --sp 2 --tp 2 "$@"
python lm.py --dp 4 --sp 2 --tp 1 --moeExperts 4 "$@"
