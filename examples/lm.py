#!/usr/bin/env python
"""Long-context transformer LM training over a (data, seq, model) mesh —
the framework's TPU-idiomatic extension beyond the reference's CNN-only
scope (SURVEY.md §2c: SP/TP/EP "explicitly absent" there; first-class
here).

One SPMD program runs data parallelism (gradient psum), sequence
parallelism (ring or all-to-all attention + cross-shard shifted targets),
tensor parallelism (Megatron sharded projections), and optionally expert
parallelism (routed MoE FFNs sharded over the data axis) — all inside a
single jitted step (distlearn_tpu/train/lm.py).

Run (8 virtual CPU devices):
    python examples/lm.py --dp 2 --sp 2 --tp 2
    python examples/lm.py --dp 4 --sp 2 --tp 1 --moeExperts 4
On the attached TPU chip:
    python examples/lm.py --tpu --dp 1 --sp 1 --tp 1 --dim 1024 --depth 8
Train then serve with continuous batching (docs/SERVING.md; tp>1
shards the decode tick too; drive with examples/lm_client.py):
    python examples/lm.py --dp 1 --sp 1 --tp 1 --serve 4 --servePort 9123
"""

from __future__ import annotations

from common import setup_platform
from distlearn_tpu.utils.flags import OBS_FLAGS, parse_flags


def main():
    opt = parse_flags("Train a transformer LM with 3D/4D parallelism.", {
        "dp": (2, "data-parallel mesh axis size"),
        "sp": (2, "sequence-parallel axis size (ring attention shards)"),
        "tp": (2, "tensor-parallel axis size (Megatron projections)"),
        "pp": (0, "pipeline-parallel stages (depth/pp blocks per stage; "
                  "requires --sp 1 --tp 1 and --depth % --pp == 0)"),
        "ppSchedule": ("gpipe", "pipeline schedule: gpipe | 1f1b (1f1b "
                       "starts each microbatch's backward as it leaves "
                       "the last stage — O(stages) activation liveness)"),
        "microbatches": (4, "pipeline microbatches per step (with --pp)"),
        "dim": (128, "model width"),
        "depth": (4, "number of blocks"),
        "vocab": (256, "vocabulary size"),
        "seqLen": (128, "global sequence length"),
        "batchSize": (8, "global batch size"),
        "steps": (30, "training steps"),
        "learningRate": (0.1, "SGD learning rate"),
        "seqImpl": ("ring", "sequence attention: ring | alltoall"),
        "seqLayout": ("contig", "sequence shard layout: contig | zigzag "
                      "(zigzag balances the causal ring so masked blocks "
                      "are never computed; needs --seqImpl ring)"),
        "attnImpl": ("", "single-device attention kernel: '' (env default)"
                     " | xla | flash | chunked (chunked = causal FLOP skip"
                     " + saved softmax weights — the measured v5e winner)"),
        "scanBlocks": (False, "scanned-depth layout: block params stacked,"
                       " depth loop as one lax.scan (program size flat in"
                       " depth; dense models only)"),
        "moeExperts": (0, "experts per MoE block (0 = dense; must equal "
                          "--dp, experts shard over the data axis)"),
        "moeTopK": (1, "experts per token (1 = Switch, 2 = GShard)"),
        "moeBalanceWeight": (0.01, "Switch load-balancing auxiliary loss "
                                   "weight (0 disables; without it top-1 "
                                   "routing collapses onto few experts)"),
        "remat": (False, "jax.checkpoint each block (long-context memory;"
                  " same as --rematMode full)"),
        "rematMode": ("", "'' | full | mlp — mlp checkpoints only the FFN "
                      "half, keeping attention residuals saved (selective "
                      "activation recomputation)"),
        "zero": (False, "train with Adam under ZeRO-1: optimizer state + "
                        "f32 masters sharded over the data axis, composed "
                        "with the sp/tp axes (train.build_lm_zero_mesh_step;"
                        " dense models only)"),
        "mixed": (False, "bf16 working params + replicated f32 masters: "
                         "every matmul pass reads 2-byte weights, the "
                         "update stays exact (train.build_lm_mixed_step / "
                         "build_lm_mixed_optax_step; not with --pp/--zero,"
                         " which manage their own param layouts)"),
        "fsdp": (False, "ZeRO-3 / fully-sharded data parallelism: params "
                        "LIVE sharded 1/dp per device, plain jit + GSPMD "
                        "inserts the gathers (train.build_lm_fsdp_step; "
                        "needs --sp 1 --tp 1, sgd, dense)"),
        "generate": (0, "after training, greedy-decode this many tokens "
                        "from held-out prompts with the KV-cached "
                        "decoder (models.greedy_generate; single-replica "
                        "param layouts: not --pp/--zero/--fsdp)"),
        "serve": (0, "after training, serve the model with this many "
                     "continuous-batching decode slots (distlearn_tpu."
                     "serve; 'G'/'R' frames, drive with examples/"
                     "lm_client.py; not --pp/--zero/--fsdp; SIGTERM or "
                     "Ctrl-C drains in-flight requests then exits)"),
        "servePort": (0, "serving port (0 = ephemeral, printed at "
                         "startup)"),
        "optimizer": ("sgd", "sgd | adam | adamw — non-sgd runs the "
                             "replicated-state optax step "
                             "(train.build_lm_optax_step; needs --tp 1)"),
        "accumSteps": (1, "gradient-accumulation microbatches per step "
                          "(memory lever; effective batch unchanged)"),
        "profile": ("", "capture a jax.profiler trace of steps 6..10 into "
                        "this directory (view in TensorBoard/Perfetto)"),
        "bf16": (False, "bfloat16 compute"),
        "tpu": (False, "run on the TPU backend"),
        "seed": (0, "init seed"),
        **OBS_FLAGS,
    })
    remat = opt.rematMode or ("full" if opt.remat else False)
    if opt.seqLayout not in ("contig", "zigzag"):
        raise SystemExit(f"--seqLayout {opt.seqLayout!r}: contig | zigzag")
    if opt.seqLayout == "zigzag":
        if opt.seqImpl != "ring":
            raise SystemExit("--seqLayout zigzag needs --seqImpl ring")
        if opt.pp or opt.zero:
            raise SystemExit("--seqLayout zigzag composes with the fused "
                             "sgd/optax steps (not --pp/--zero)")
    if opt.scanBlocks and (opt.moeExperts or opt.pp):
        raise SystemExit("--scanBlocks needs a homogeneous dense stack "
                         "and the non-pp step (pipeline stages shard the "
                         "per-block layout)")
    if opt.ppSchedule not in ("gpipe", "1f1b"):
        raise SystemExit(f"--ppSchedule {opt.ppSchedule!r}: gpipe | 1f1b")
    if opt.mixed and (opt.pp or opt.zero):
        raise SystemExit("--mixed composes with the fused sgd/optax steps "
                         "(--pp stages and --zero shards manage their own "
                         "parameter layouts)")
    if opt.fsdp and (opt.sp != 1 or opt.tp != 1 or opt.pp or opt.zero
                     or opt.mixed or opt.moeExperts
                     or opt.optimizer != "sgd"):
        raise SystemExit("--fsdp shards the whole model over the data "
                         "axis: pass --sp 1 --tp 1 and no "
                         "--pp/--zero/--mixed/--moeExperts/--optimizer")
    if opt.pp:
        if opt.sp != 1 or opt.tp != 1:
            raise SystemExit("--pp composes with data parallelism only: "
                             "pass --sp 1 --tp 1 (PP and TP/SP cover "
                             "different model regimes)")
        if opt.depth % opt.pp:
            raise SystemExit(f"--pp {opt.pp} needs --depth divisible by "
                             f"{opt.pp} (equal blocks per stage)")
        if (opt.accumSteps != 1 or opt.moeExperts or opt.zero
                or opt.optimizer != "sgd"):
            raise SystemExit("--pp does not support --accumSteps/"
                             "--moeExperts/--zero/--optimizer (GPipe "
                             "microbatching IS the accumulation lever on "
                             "this path; MoE/ZeRO/optax need the non-pp "
                             "step)")
        if remat == "mlp":
            raise SystemExit("--rematMode mlp is the non-pp step's "
                             "selective mode; the pipeline stage fn "
                             "checkpoints whole blocks — use --remat "
                             "(full) with --pp")
    if opt.serve and (opt.pp or opt.zero or opt.fsdp):
        raise SystemExit("--serve needs a single-replica param layout "
                         "(not --pp/--zero/--fsdp)")
    if opt.serve and opt.moeExperts:
        raise SystemExit("--serve supports dense models (per-tick MoE "
                         "routing would not match the trained capacity "
                         "math)")
    n_dev = opt.dp * opt.sp * opt.tp * max(1, opt.pp)
    setup_platform(n_dev, opt.tpu)
    from easgd_common import obs_finish, obs_setup
    obs_http = obs_setup(opt)

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import random
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from contextlib import ExitStack

    from distlearn_tpu.models.transformer import (lm_loss, param_specs,
                                                  transformer_lm)
    from distlearn_tpu.train.lm import (build_lm_moe_metrics,
                                        build_lm_pp_1f1b_step,
                                        build_lm_pp_step, build_lm_step,
                                        stack_blocks)
    from distlearn_tpu.utils.logging import root_print
    from distlearn_tpu.utils.profiling import StepTimer, trace

    log = root_print(0)
    if opt.moeExperts and opt.moeExperts != opt.dp:
        raise SystemExit(f"--moeExperts {opt.moeExperts} must equal --dp "
                         f"{opt.dp} (one expert per data-parallel device)")
    devs = jax.devices()
    if len(devs) < n_dev:
        raise SystemExit(f"need {n_dev} devices (dp*sp*tp*pp), "
                         f"have {len(devs)}")
    cdtype = jnp.bfloat16 if opt.bf16 else None
    lm = transformer_lm(
        vocab=opt.vocab, dim=opt.dim, depth=opt.depth,
        heads=max(4, opt.dim // 64), max_len=opt.seqLen,
        compute_dtype=cdtype,
        seq_impl=opt.seqImpl, remat=remat,
        attn_impl=opt.attnImpl or None, scan_blocks=opt.scanBlocks,
        moe_experts=opt.moeExperts, moe_top_k=opt.moeTopK)
    params, _ = lm.init(random.PRNGKey(opt.seed))
    if opt.pp:
        mesh = Mesh(np.array(devs[:n_dev]).reshape(opt.dp, opt.pp),
                    ("data", "pipe"))
        log(f"mesh dp={opt.dp} pipe={opt.pp} on {devs[0].platform}; "
            f"{opt.microbatches} microbatches")
        shared, stacked = stack_blocks(params, opt.depth)
        shared = jax.device_put(shared, NamedSharding(mesh, P()))
        stacked = jax.device_put(stacked, NamedSharding(mesh, P("pipe")))
        builder = (build_lm_pp_1f1b_step if opt.ppSchedule == "1f1b"
                   else build_lm_pp_step)
        pp_step = builder(mesh, shared, stacked,
                          lr=opt.learningRate,
                          num_microbatches=opt.microbatches,
                          compute_dtype=cdtype, remat=bool(remat))
        state = {"shared": shared, "stacked": stacked}

        def step(st, tokens):
            sh, stk, loss = pp_step(st["shared"], st["stacked"], tokens)
            return {"shared": sh, "stacked": stk}, loss
        params = state
        tok_spec = P("data")
    else:
        mesh = Mesh(np.array(devs[:n_dev]).reshape(opt.dp, opt.sp, opt.tp),
                    ("data", "seq", "model"))
        log(f"mesh dp={opt.dp} sp={opt.sp} tp={opt.tp} on "
            f"{devs[0].platform}; seq_impl={opt.seqImpl}"
            + (f"; {opt.moeExperts} experts" if opt.moeExperts else ""))
        if opt.attnImpl and opt.sp > 1:
            log(f"NOTE: --attnImpl {opt.attnImpl} is inert with --sp "
                f"{opt.sp} > 1 — the ring/all-to-all blockwise path "
                "takes over (see parallel/sequence.py ring_attention)")
        elif opt.attnImpl == "chunked":
            from distlearn_tpu.parallel.sequence import (chunked_engages,
                                                         resolve_chunk)
            _L = opt.seqLen // max(1, opt.sp)
            if not chunked_engages(_L):
                log(f"NOTE: --attnImpl chunked falls back to xla at "
                    f"local length {_L} with chunk {resolve_chunk(_L)} "
                    "(needs L > chunk and L % chunk == 0); use a longer "
                    "--seqLen or set DISTLEARN_TPU_CHUNK")
        ep_axis = "data" if opt.moeExperts else None
        placed = jax.device_put(
            params, jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s),
                param_specs(params, tp_axis="model", ep_axis=ep_axis)))
        if opt.zero:
            if opt.moeExperts or opt.accumSteps != 1 \
                    or opt.optimizer != "sgd":
                raise SystemExit("--zero supports dense models without "
                                 "--accumSteps/--moeExperts, and picks its "
                                 "own optimizer (Adam against the sharded "
                                 "f32 masters) — drop --optimizer")
            import optax

            from distlearn_tpu.train import (build_lm_zero_mesh_step,
                                             init_lm_zero_mesh_state)
            if opt.learningRate > 0.01:
                log(f"NOTE: --learningRate {opt.learningRate} is large "
                    "for Adam; --zero usually wants ~1e-3 (large Adam "
                    "steps diverge)")
            tx = optax.adam(opt.learningRate)
            step = build_lm_zero_mesh_step(lm, mesh, params, tx)
            params = init_lm_zero_mesh_state(placed, mesh, tx)
            log("ZeRO-1: Adam state + f32 masters sharded over the data "
                "axis (composed with sp/tp)")
        elif opt.optimizer != "sgd":
            if opt.tp != 1 or opt.moeExperts:
                raise SystemExit(f"--optimizer {opt.optimizer} uses the "
                                 "replicated-state optax step: pass --tp 1 "
                                 "(TP needs --zero's sharded masters) and "
                                 "no --moeExperts (expert-sharded state)")
            import optax

            from distlearn_tpu.train import (LMOptaxState,
                                             build_lm_optax_step)
            makers = {"adam": optax.adam, "adamw": optax.adamw}
            if opt.optimizer not in makers:
                raise SystemExit(f"unknown --optimizer {opt.optimizer!r} "
                                 f"(sgd | {' | '.join(makers)})")
            tx = makers[opt.optimizer](opt.learningRate)
            if opt.mixed:
                from distlearn_tpu.train import (
                    build_lm_mixed_optax_step, init_lm_mixed_optax_state)
                step = build_lm_mixed_optax_step(
                    lm, mesh, tx, accum_steps=opt.accumSteps,
                    seq_layout=opt.seqLayout)
                params = init_lm_mixed_optax_state(placed, tx)
                log(f"{opt.optimizer}, mixed precision: bf16 working "
                    "params + f32 masters")
            else:
                step = build_lm_optax_step(lm, mesh, tx,
                                           accum_steps=opt.accumSteps,
                                           seq_layout=opt.seqLayout)
                params = LMOptaxState(placed, tx.init(placed))
                log(f"{opt.optimizer} via the replicated-state optax "
                    "LM step")
        elif opt.fsdp:
            from distlearn_tpu.train import (build_lm_fsdp_step,
                                             init_lm_fsdp_params)
            step = build_lm_fsdp_step(lm, mesh, params,
                                      lr=opt.learningRate,
                                      accum_steps=opt.accumSteps)
            params = init_lm_fsdp_params(params, mesh)
            log("ZeRO-3/FSDP: params live sharded 1/dp per device; "
                "jit+GSPMD inserts the gathers")
        elif opt.mixed:
            from distlearn_tpu.train import (build_lm_mixed_step,
                                             init_lm_mixed_state)
            step = build_lm_mixed_step(
                lm, mesh, params, lr=opt.learningRate,
                ep_axis=ep_axis, accum_steps=opt.accumSteps,
                moe_balance_weight=(opt.moeBalanceWeight
                                    if opt.moeExperts else 0.0),
                seq_layout=opt.seqLayout)
            params = init_lm_mixed_state(placed)
            log("mixed precision: bf16 working params + f32 masters "
                "(matmuls read 2-byte weights; the update stays exact)")
        else:
            step = build_lm_step(
                lm, mesh, params, lr=opt.learningRate,
                ep_axis=ep_axis, accum_steps=opt.accumSteps,
                moe_balance_weight=(opt.moeBalanceWeight
                                    if opt.moeExperts else 0.0),
                seq_layout=opt.seqLayout)
            params = placed
        tok_spec = P("data", "seq")
        if opt.moeExperts:
            # template = the raw placed params (the train state may wrap
            # them, e.g. LMMixedState)
            moe_metrics = build_lm_moe_metrics(lm, mesh, placed,
                                               ep_axis=ep_axis)

    # Synthetic corpus: order-2 Markov tokens — learnable next-token
    # structure without any dataset download (zero-egress env).
    rng = np.random.RandomState(opt.seed)
    trans = rng.dirichlet(np.ones(opt.vocab) * 0.05,
                          size=opt.vocab).astype(np.float64)
    toks = np.zeros((opt.batchSize, opt.seqLen), np.int32)
    toks[:, 0] = rng.randint(0, opt.vocab, opt.batchSize)
    for t in range(1, opt.seqLen):
        for b in range(opt.batchSize):
            toks[b, t] = rng.choice(opt.vocab, p=trans[toks[b, t - 1]])
    if opt.seqLayout == "zigzag":
        from distlearn_tpu.parallel.sequence import zigzag_indices
        toks = toks[:, zigzag_indices(opt.sp, opt.seqLen)]
        log("zigzag sequence layout: balanced causal ring (masked blocks "
            "never computed)")
    tokens = jax.device_put(jnp.asarray(toks),
                            NamedSharding(mesh, tok_spec))

    timer = StepTimer()
    do_profile = bool(opt.profile) and opt.steps >= 6
    if opt.profile and not do_profile:
        log(f"--profile ignored: needs --steps >= 6 (warmup is steps 1-5), "
            f"got {opt.steps}")
    prof_stop = min(10, opt.steps)
    with ExitStack() as stack:            # guarantees stop_trace on error
        for i in range(1, opt.steps + 1):
            if do_profile and i == 6:     # skip compile + warmup steps
                # drain the async queue so warmup work isn't in the trace
                jax.block_until_ready(jax.tree_util.tree_leaves(params)[0])
                timer.reset_window()      # drain time is not a step
                stack.enter_context(trace(opt.profile))
            timer.tick()
            params, loss = step(params, tokens)
            if do_profile and i == prof_stop:
                jax.block_until_ready(jax.tree_util.tree_leaves(params)[0])
                timer.reset_window()
                stack.close()
                log(f"profiler trace written to {opt.profile}")
            if i % 10 == 0 or i == opt.steps:
                extra = ""
                if opt.moeExperts and not opt.pp:
                    m = jax.device_get(moe_metrics(
                        getattr(params, "params", params), tokens))
                    extra = (f" [router balance "
                             f"{float(m['moe_balance_loss']):.3f}, dropped "
                             f"{float(m['moe_dropped_frac']):.3f}]")
                log(f"step {i}: loss {float(loss):.4f}{extra} "
                    f"({timer.steps_per_sec():.2f} steps/s)")
    jax.block_until_ready(jax.tree_util.tree_leaves(params)[0])
    if opt.generate:
        if opt.pp or opt.zero or opt.fsdp:
            raise SystemExit("--generate needs a single-replica param "
                             "layout (not --pp/--zero/--fsdp)")
        if opt.moeExperts:
            raise SystemExit("--generate supports dense models (per-tick "
                             "MoE routing would not match the trained "
                             "capacity math)")
        if opt.seqLayout == "zigzag":
            raise SystemExit("--generate decodes in natural order — drop "
                             "--seqLayout zigzag")
        from distlearn_tpu.models import greedy_generate
        # the trained params: unwrap mixed/optax states to the plain
        # tree, and GATHER any tp/sp-sharded leaves to the host — the
        # decoder runs single-replica regardless of the train mesh
        p = jax.device_get(getattr(params, "params", params))
        Pq = max(4, opt.seqLen // 8)
        steps = min(opt.generate, opt.seqLen - Pq)
        # two prompts of different lengths, left-padded to Pq: the
        # batched ragged path (prompt_lens) in one call
        plens = np.array([Pq, max(2, Pq // 2)], np.int32)
        prompts = np.zeros((2, Pq), np.int32)
        for b, L in enumerate(plens):
            prompts[b, Pq - L:] = toks[b % toks.shape[0], :L]
        gen = greedy_generate(p, jnp.asarray(prompts), steps,
                              attn_impl=opt.attnImpl or None,
                              prompt_lens=plens)
        for b, L in enumerate(plens):
            log(f"generated {gen.shape[1]} tokens (KV-cached greedy, "
                f"prompt len {L}): {np.asarray(gen[b]).tolist()}")
    if opt.serve:
        from distlearn_tpu.parallel.ha import install_signal_flush
        from distlearn_tpu.serve import DecodeEngine, ServeServer
        p = jax.device_get(getattr(params, "params", params))
        mesh_kw = {}
        if opt.tp > 1:
            # serve tp-sharded over a dedicated ("model",) submesh: the
            # decode tick is one jit/shard_map program, psums and all
            from jax.sharding import Mesh as _Mesh
            mesh_kw = {"mesh": _Mesh(np.array(jax.devices()[:opt.tp]),
                                     ("model",)),
                       "tp_axis": "model"}
        engine = DecodeEngine(p, num_slots=opt.serve, **mesh_kw)
        # warm the smallest prefill bucket + the tick program so the
        # first real request's TTFT is a tick, not a compile
        _slot, _ = engine.admit(np.ones(4, np.int32), 2)
        engine.tick()
        engine.finish(_slot)
        srv = ServeServer(engine, port=opt.servePort).start()
        install_signal_flush(srv)    # SIGTERM -> drain, then exit
        log(f"serving on {srv.host}:{srv.port} "
            f"({opt.serve} slots, max_len {engine.max_len}"
            + (f", tp={opt.tp}" if opt.tp > 1 else "") + ") — "
            f"drive with: python examples/lm_client.py "
            f"--port {srv.port}")
        try:
            while srv._thread is not None and srv._thread.is_alive():
                srv._thread.join(0.5)
        except KeyboardInterrupt:
            log("draining...")
            srv.checkpoint_now(wait=True)
        srv.stop()
        log("serve drained")
    obs_finish(opt, obs_http)
    log("done")


if __name__ == "__main__":
    main()
