#!/usr/bin/env python
"""AsyncEA evaluation process — counterpart of examples/EASGD_tester.lua.

Blocks on the test channel; every server push it evaluates the center on the
train and test sets, appends error rates to a JSONL log (the reference's
optim.Logger + gnuplot plots, EASGD_tester.lua:40-47,161-165), and acks.
Render the curves with ``python tools/plot_errors.py <log>.jsonl``.

Run:  python easgd_tester.py --numNodes 2 --port 9500 --numTests 5 ...
"""

from __future__ import annotations

from easgd_common import build_model_and_data, setup_platform, DATA_FLAGS
from distlearn_tpu.utils.flags import (parse_flags, NODE_FLAGS, TRAIN_FLAGS,
                                       EA_FLAGS, ASYNC_FLAGS)


def main():
    opt = parse_flags("EASGD evaluation process.", {
        **NODE_FLAGS, **TRAIN_FLAGS, **EA_FLAGS, **ASYNC_FLAGS, **DATA_FLAGS,
        "numTests": (5, "number of test rounds to serve before exiting"),
        "log": ("", "JSONL metrics path (default: <save>/tester.jsonl or off)"),
    })
    setup_platform(1, opt.tpu)

    import jax
    import numpy as np
    from jax import random

    from distlearn_tpu.data import (PermutationSampler, batch_iterator,
                                    make_dataset, synthetic_cifar10,
                                    synthetic_mnist)
    from distlearn_tpu.parallel.async_ea import AsyncEATester
    from distlearn_tpu.utils import metrics as M
    from distlearn_tpu.utils.logging import (MetricsLogger, print_tester,
                                             set_verbose)

    set_verbose(True)
    model, params, mstate, ds, nc = build_model_and_data(opt)
    synth = synthetic_cifar10 if opt.model == "cifar" else synthetic_mnist
    xte, yte, _ = synth(max(256, opt.numExamples // 4), seed=opt.seed + 1)
    ds_test = make_dataset(xte, yte, nc)

    log_path = opt.log or (f"{opt.save}/tester.jsonl" if opt.save else None)
    logger = MetricsLogger(log_path)

    @jax.jit
    def eval_batch(p, s, x, y):
        log_probs, _ = model.apply(p, s, x, train=False)
        return log_probs

    def error_rate(p, s, dset):
        cm = np.zeros((nc, nc), np.int64)
        sampler = PermutationSampler(dset.size, seed=0)
        for bx, by in batch_iterator(dset, sampler, opt.batchSize):
            lp = np.asarray(eval_batch(p, s, bx, by))
            preds = lp.argmax(-1)
            np.add.at(cm, (by, preds), 1)
        return 1.0 - M.total_valid(cm)

    # tester advertisement only works against a same-version server —
    # "legacy" (or raw against old fleets) keeps the pre-packed wire
    codec = None if opt.wireCodec in ("legacy", "raw") else opt.wireCodec
    tester = AsyncEATester(opt.host, opt.port, opt.numNodes, codec=codec)
    for round_i in range(1, opt.numTests + 1):
        try:
            params = tester.start_test(params)   # blocks for server push
        except OSError as e:
            # the center died (HA failover: a promoted standby serves
            # WORKERS, not the test channel — docs/HA.md); the rounds
            # already logged are the deliverable, so exit clean rather
            # than crash the demo pipeline
            print_tester(f"center gone after {round_i - 1} rounds "
                         f"({e!r}); exiting")
            break
        train_err = error_rate(params, mstate, ds)
        test_err = error_rate(params, mstate, ds_test)
        rec = logger.add(round=round_i, train_error=train_err,
                         test_error=test_err)
        print_tester(f"round {round_i}: train_err={train_err:.4f} "
                     f"test_err={test_err:.4f}")
        tester.finish_test()
    print_tester("done")
    logger.close()
    tester.close()


if __name__ == "__main__":
    main()
