#!/usr/bin/env python
"""AsyncEA worker process — counterpart of examples/EASGD_client.lua.

Local training on this process's data partition; every ``--communicationTime``
steps the client runs the sync handshake against the parameter server.  Note
the reference ordering kept here: the sync happens BETWEEN gradient
computation and the local SGD update (EASGD_client.lua:106-117).

Run:  python easgd_client.py --nodeIndex 1 --numNodes 2 --port 9500 ...
"""

from __future__ import annotations

from easgd_common import (build_model_and_data, setup_platform, DATA_FLAGS,
                          obs_finish, obs_setup)
from distlearn_tpu.utils.flags import (parse_flags, NODE_FLAGS, TRAIN_FLAGS,
                                       EA_FLAGS, ASYNC_FLAGS, OBS_FLAGS)


def main():
    opt = parse_flags("EASGD worker client.", {
        **NODE_FLAGS, **TRAIN_FLAGS, **EA_FLAGS, **ASYNC_FLAGS, **DATA_FLAGS,
        **OBS_FLAGS,
        "autoRejoin": (1, "on a failed sync (server evicted this client, "
                          "connection reset, timeout), re-dial and "
                          "Rejoin? instead of crashing — local params "
                          "reset to the CURRENT center, training "
                          "continues.  --autoRejoin 0 = fail fast"),
        "centers": ("", "comma-separated standby centers (host:port or "
                        "just port) to fail over to when the primary "
                        "dies for good (docs/HA.md); with --autoRejoin, "
                        "a dead rejoin falls back to walking this list"),
        "joinFleet": (False, "enter a RUNNING --elastic server through "
                             "the Join? handshake instead of the founding "
                             "Enter? admission: the server assigns the "
                             "cid and this client adopts the live center "
                             "before training (docs/ELASTIC.md)"),
        "leaveAfter": (0.0, "seconds of training after which this client "
                            "departs gracefully via Leave? — the pending "
                            "delta is flushed through the server's "
                            "ledger, not dropped (0 = train to the end)"),
        "capacity": (1.0, "advertised capacity weight: an elastic server "
                          "scales this client's deltas by "
                          "cap*N/sum(live caps) so heterogeneous fleets "
                          "keep the fixed-fleet alpha budget"),
        "adaptiveTau": (False, "straggler adaptation: stretch the "
                               "effective tau (bounded by alpha*tau<=0.9) "
                               "when syncs run slower than this client's "
                               "best-ever pace"),
    })
    setup_platform(1, opt.tpu)
    obs_http = obs_setup(opt)

    import jax
    import numpy as np
    from jax import random

    from distlearn_tpu.comm import ProtocolError
    from distlearn_tpu.data import PermutationSampler, batch_iterator
    from distlearn_tpu.models.core import loss_fn
    from distlearn_tpu.parallel.async_ea import AsyncEAClient
    from distlearn_tpu.utils.logging import print_client, set_verbose

    set_verbose(opt.verbose)
    # a joiner's nodeIndex may run past the founding fleet (the server
    # assigns the real cid anyway) — wrap it onto a valid data partition
    part = (opt.nodeIndex - 1) % opt.numNodes
    model, params, mstate, ds, nc = build_model_and_data(
        opt, partition=part, partitions=opt.numNodes)

    codec = None if opt.wireCodec == "legacy" else opt.wireCodec
    # --shards 0 opts this client out of striped syncs (it still joins a
    # sharded server — the Enter reply simply omits the stripe plan and
    # the sync runs on the dedicated conn alone); any other value lets
    # the server's advertised plan decide.
    centers = []
    for tok in opt.centers.split(","):
        tok = tok.strip()
        if tok:
            h, _, pp = tok.rpartition(":")
            centers.append((h or opt.host, int(pp)))
    if opt.joinFleet:
        client, params = AsyncEAClient.join(
            opt.host, opt.port, params, opt.communicationTime, opt.alpha,
            capacity=opt.capacity, codec=codec, overlap=opt.overlapSync,
            sharded=opt.shards != 0, adaptive_tau=opt.adaptiveTau,
            centers=centers or None)
        opt.nodeIndex = client.node    # the server assigned the real cid
    else:
        client = AsyncEAClient(opt.host, opt.port, node=opt.nodeIndex,
                               tau=opt.communicationTime, alpha=opt.alpha,
                               codec=codec, overlap=opt.overlapSync,
                               sharded=opt.shards != 0,
                               capacity=opt.capacity,
                               adaptive_tau=opt.adaptiveTau,
                               centers=centers or None)
        params = client.init_client(params)

    @jax.jit
    def grad_step(p, s, x, y, rng):
        (loss, (_, new_s)), grads = jax.value_and_grad(
            lambda pp: loss_fn(model, pp, s, x, y, train=True, rng=rng),
            has_aux=True)(p)
        return grads, new_s, loss

    @jax.jit
    def apply_sgd(p, g):
        return jax.tree_util.tree_map(
            lambda pp, gg: pp - np.float32(opt.learningRate) * gg, p, g)

    import time as _time
    rng = random.PRNGKey(opt.seed + opt.nodeIndex)
    step = 0
    t0 = _time.monotonic()
    left = False
    for epoch in range(1, opt.numEpochs + 1):
        if left:
            break
        sampler = PermutationSampler(ds.size, seed=opt.seed + epoch)
        for bx, by in batch_iterator(ds, sampler, opt.batchSize):
            if opt.leaveAfter and _time.monotonic() - t0 >= opt.leaveAfter:
                print_client(opt.nodeIndex,
                             f"leave drill: departing after {step} steps")
                client.leave()
                left = True
                break
            rng, sub = random.split(rng)
            grads, mstate, loss = grad_step(params, mstate, bx, by, sub)
            # sync BETWEEN grads and update (EASGD_client.lua:109 then :113)
            try:
                params, synced = client.sync_client(params)
            except (OSError, ProtocolError) as e:
                # OSError covers TimeoutError/ConnectionError.  An
                # evicted/cut worker is not dead: re-admit and take the
                # CURRENT center — and skip this iteration's update,
                # whose gradient was computed at the stale params the
                # reset just discarded (applying it would re-inject the
                # lost state in gradient form)
                if not opt.autoRejoin:
                    raise
                print_client(opt.nodeIndex,
                             f"sync failed ({e!r}); rejoining")
                try:
                    # with standbys configured, don't grind through the
                    # full retry budget against a center that may be dead
                    # for good — fail over while the promoted standby is
                    # still holding its rejoin window open
                    params = client.rejoin(params,
                                           retries=6 if centers else 60)
                except (OSError, ProtocolError):
                    # the primary itself is gone: walk the dial list to
                    # a (possibly freshly promoted) standby — LOCAL
                    # params and residuals survive this path, only the
                    # rejoin above resets to the center (docs/HA.md)
                    params = client.failover(params)
                step += 1
                continue
            params = apply_sgd(params, grads)
            step += 1
            if synced:
                print_client(opt.nodeIndex,
                             f"step {step} loss {float(loss):.4f} (synced)")
    print_client(opt.nodeIndex, "done")
    if not left:              # leave() already closed every channel
        client.close()
    obs_finish(opt, obs_http)


if __name__ == "__main__":
    main()
