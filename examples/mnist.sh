#!/bin/bash
# Reference parity: examples/mnist.sh launches 4 node processes; the
# TPU-native framework drives a 4-node mesh from one SPMD program.
cd "$(dirname "$0")"
python mnist.py --numNodes 4 --numEpochs 4 "$@"
