#!/usr/bin/env python
"""AsyncEA parameter-server process — counterpart of examples/EASGD_server.lua.

Holds the authoritative center variable, admits one client at a time, applies
elastic deltas, pushes the center to the tester every ``--testTime`` syncs
(EASGD_server.lua:118-128).  Does no training.  Checkpoints the center
(first-class here; commented-out in the reference, EASGD_server.lua:37-48).

Run:  python easgd_server.py --numNodes 2 --port 9500 [--tester] ...
"""

from __future__ import annotations

from easgd_common import (build_model_and_data, setup_platform, DATA_FLAGS,
                          obs_finish, obs_setup)
from distlearn_tpu.utils.flags import (parse_flags, NODE_FLAGS, TRAIN_FLAGS,
                                       EA_FLAGS, ASYNC_FLAGS, OBS_FLAGS)


def main():
    opt = parse_flags("EASGD parameter server.", {
        **NODE_FLAGS, **TRAIN_FLAGS, **EA_FLAGS, **ASYNC_FLAGS, **DATA_FLAGS,
        **OBS_FLAGS,
        "numSyncs": (0, "total syncs to serve (0 = numEpochs*steps/tau per node)"),
        "tester": (False, "open the test channel and expect a tester process"),
        "concurrent": (False, "serve clients on overlapped per-client "
                              "worker threads (AsyncEAServerConcurrent) "
                              "instead of the reference's one-at-a-time "
                              "critical section"),
        "syncTimeout": (0.0, "max seconds to wait for any sync request "
                             "before stopping the serve loop (0 = wait "
                             "forever, the reference's behavior — set it "
                             "when clients may die without cleanup)"),
        "centerCkpt": ("", "HA checkpoint directory (docs/HA.md): "
                           "periodically checkpoint the center + failover "
                           "ledger there and flush once more on SIGTERM; "
                           "a --standby process tails the same directory"),
        "ckptEvery": (8, "checkpoint the center every N applied syncs "
                         "(with --centerCkpt)"),
        "standby": (False, "start as a warm standby (requires "
                           "--concurrent and --centerCkpt): bind "
                           "listeners but admit nobody, wait for a "
                           "checkpoint, promote into the next center "
                           "epoch, then serve rejoining clients"),
        "watchPrimary": ("", "standby only: probe this primary "
                             "(host:port or just port) and promote when "
                             "it stops answering, instead of promoting "
                             "on the first checkpoint seen"),
        "elastic": (False, "admit Join?/Leave? membership changes mid-run "
                           "(requires --concurrent): joiners adopt the "
                           "live center through the join fence, leavers "
                           "flush their pending delta through the ledger "
                           "before departing (docs/ELASTIC.md)"),
    })
    setup_platform(1, opt.tpu)
    obs_http = obs_setup(opt)

    from distlearn_tpu.parallel.async_ea import (AsyncEAServer,
                                                 AsyncEAServerConcurrent)
    from distlearn_tpu.utils import checkpoint as ckpt
    from distlearn_tpu.utils.logging import print_server, set_verbose

    set_verbose(True)  # server logs are the reference's printServer
    model, params, mstate, ds, nc = build_model_and_data(opt)

    # Each client trains on its own partition (last partition takes the
    # remainder rows — data.make_dataset) and syncs every tau of its
    # continuously-counted steps, so the server must expect exactly
    # sum_i (numEpochs * steps_i) // tau handshakes.
    per = ds.size // opt.numNodes
    sizes = [per] * (opt.numNodes - 1) + [ds.size - per * (opt.numNodes - 1)]
    num_syncs = opt.numSyncs or sum(
        (opt.numEpochs * (sz // max(1, opt.batchSize)))
        // opt.communicationTime for sz in sizes)
    print_server(f"serving {opt.numNodes} clients, {num_syncs} syncs, "
                 f"tester={opt.tester}")

    if opt.elastic and not opt.concurrent:
        raise SystemExit("--elastic requires --concurrent")
    if opt.standby and not (opt.concurrent and opt.centerCkpt):
        raise SystemExit("--standby requires --concurrent and --centerCkpt")
    if opt.standby and opt.tester:
        raise SystemExit("--standby is incompatible with --tester "
                         "(no test channel is accepted pre-promotion)")

    if opt.concurrent:
        import time as _time
        from distlearn_tpu.parallel import ha
        srv = AsyncEAServerConcurrent(opt.host, opt.port, opt.numNodes,
                                      with_tester=opt.tester,
                                      shards=max(1, opt.shards),
                                      standby=opt.standby,
                                      elastic=opt.elastic)
        if opt.standby:
            sb = ha.StandbyCenter(srv, opt.centerCkpt, params)
            if opt.watchPrimary:
                h, _, pp = opt.watchPrimary.rpartition(":")
                h = h or opt.host
                print_server(f"standby: watching primary {h}:{pp}, "
                             f"tailing {opt.centerCkpt}")
                params = sb.watch(lambda: ha.tcp_probe(h, int(pp)))
            else:
                print_server("standby: waiting for a checkpoint in "
                             f"{opt.centerCkpt}")
                sb.wait_for_checkpoint()
                params = sb.promote()
        else:
            srv.init_server(params)
        if opt.centerCkpt:
            srv.enable_checkpoint(opt.centerCkpt,
                                  every=max(1, opt.ckptEvery))
            ha.install_signal_flush(srv)
        srv.start()
        if opt.standby:
            # rejoining clients arrive through the dispatcher's grace
            # poll; don't let the live_clients==0 stop fire before the
            # fleet has had a chance to re-dial
            deadline = _time.time() + (opt.syncTimeout or 60.0)
            while srv.live_clients == 0 and _time.time() < deadline:
                _time.sleep(0.05)
        tests_pushed = last_ckpt = last_done = 0
        last_progress = _time.time()
        while srv.syncs_completed < num_syncs and srv.live_clients > 0:
            if srv.drained:
                # every client finished/died and nothing is in flight —
                # the concurrent analogue of the serial loop's
                # RuntimeError-from-recv_any stop
                print_server(f"stopping after {srv.syncs_completed} syncs "
                             "(all clients done)")
                break
            done = srv.syncs_completed
            if done > last_done:            # idle timeout, not wall clock:
                last_done = done            # progress resets the clock
                last_progress = _time.time()
            if opt.syncTimeout and \
                    _time.time() - last_progress > opt.syncTimeout:
                print_server(f"stopping after {done} syncs (no sync for "
                             f"{opt.syncTimeout:.0f}s)")
                break
            if opt.tester and done // opt.testTime > tests_pushed:
                tests_pushed += 1
                srv.test_net()
            if opt.save and done - last_ckpt >= opt.testTime * 2:
                last_ckpt = done
                ckpt.save_checkpoint(opt.save, done,
                                     {"center": srv.current_center(params)})
            _time.sleep(0.01)
        params = srv.current_center(params)
        served = srv.syncs_completed
        if opt.tester:
            # match the serial loop's push count exactly: one per testTime
            # syncs plus the final eval push (the tester counts rounds)
            while tests_pushed < served // opt.testTime:
                tests_pushed += 1
                srv.test_net()
            srv.test_net()
        if opt.save:
            ckpt.save_checkpoint(opt.save, served, {"center": params})
        print_server("done")
        srv.stop()
        srv.close()
        obs_finish(opt, obs_http)
        return

    srv = AsyncEAServer(opt.host, opt.port, opt.numNodes,
                        with_tester=opt.tester, shards=max(1, opt.shards))
    srv.init_server(params)
    if opt.centerCkpt:
        from distlearn_tpu.parallel import ha
        srv.enable_checkpoint(opt.centerCkpt, every=max(1, opt.ckptEvery))
        ha.install_signal_flush(srv)
    served = 0
    for i in range(1, num_syncs + 1):
        try:
            params = srv.sync_server(params,
                                     timeout=opt.syncTimeout or None)
        except (TimeoutError, RuntimeError) as e:
            # evicted/finished clients can leave fewer syncs than the
            # expected count — stop serving instead of wedging (the
            # reference would hang here); RuntimeError = every client gone
            print_server(f"stopping serve loop after {served} syncs: {e!r}")
            break
        served = i
        if opt.tester and i % opt.testTime == 0:
            srv.test_net()
        if opt.save and i % (opt.testTime * 2) == 0:
            ckpt.save_checkpoint(opt.save, i, {"center": params})
    if opt.tester:
        srv.test_net()  # final eval push
    if opt.save:
        # stamped with the count actually served: an early stop must not
        # masquerade as a fully-served run
        ckpt.save_checkpoint(opt.save, served, {"center": params})
    print_server("done")
    srv.close()
    obs_finish(opt, obs_http)


if __name__ == "__main__":
    main()
