// distcomm — native transport core for distlearn_tpu.comm.
//
// The reference framework's communication backend is torch-ipc, a C++
// library doing all socket IO and tree reductions under Lua bindings
// (SURVEY.md §2b).  This is its TPU-framework counterpart: the byte-moving
// hot path (frame assembly, full-buffer send/recv loops, and the host-side
// in-memory tree reduction used by the DCN control plane) in C++, loaded
// from Python via ctypes (no pybind11 in this environment).
//
// Wire protocol (must match distlearn_tpu/comm/transport.py):
//   frame := kind:u8 | length:u64le | payload[length]
//
// All functions return 0 on success, -1 on clean peer-close (FIN before
// any byte of the requested read), -2 on mid-read peer-close (FIN after
// partial progress — a frame was torn, distinct from a finished peer), or
// -errno.

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

// Full-write loop over writev: header + payload in one syscall when possible.
int write_all(int fd, iovec *iov, int iovcnt) {
  while (iovcnt > 0) {
    ssize_t n = ::writev(fd, iov, iovcnt);
    if (n < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    size_t left = static_cast<size_t>(n);
    while (iovcnt > 0 && left >= iov->iov_len) {
      left -= iov->iov_len;
      ++iov;
      --iovcnt;
    }
    if (iovcnt > 0) {
      iov->iov_base = static_cast<uint8_t *>(iov->iov_base) + left;
      iov->iov_len -= left;
    }
  }
  return 0;
}

} // namespace

extern "C" {

int dc_send_frame(int fd, uint8_t kind, const uint8_t *payload, uint64_t len) {
  uint8_t header[9];
  header[0] = kind;
  std::memcpy(header + 1, &len, 8); // little-endian hosts only (x86/ARM LE)
  iovec iov[2] = {{header, sizeof(header)},
                  {const_cast<uint8_t *>(payload), static_cast<size_t>(len)}};
  return write_all(fd, iov, len ? 2 : 1);
}

// Two-part frame (tensor path): header(9) + meta + raw tensor bytes in one
// writev — lets Python pass the numpy buffer pointer zero-copy.
int dc_send_frame2(int fd, uint8_t kind, const uint8_t *meta, uint64_t mlen,
                   const uint8_t *data, uint64_t dlen) {
  uint8_t header[9];
  header[0] = kind;
  uint64_t total = mlen + dlen;
  std::memcpy(header + 1, &total, 8);
  iovec iov[3] = {{header, sizeof(header)},
                  {const_cast<uint8_t *>(meta), static_cast<size_t>(mlen)},
                  {const_cast<uint8_t *>(data), static_cast<size_t>(dlen)}};
  return write_all(fd, iov, dlen ? 3 : (mlen ? 2 : 1));
}

int dc_recv_exact(int fd, uint8_t *buf, uint64_t len) {
  uint64_t got = 0;
  while (got < len) {
    ssize_t n = ::recv(fd, buf + got, len - got, 0);
    if (n == 0) return got ? -2 : -1; // peer closed (mid-read vs clean)
    if (n < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    got += static_cast<uint64_t>(n);
  }
  return 0;
}

// In-place elementwise reduction kernels for the host-side tree reduce
// (the reference runs user Lua closures per tensor pair; here: fixed
// native kernels selected by op code — 0=sum, 1=max, 2=min).
#define DC_REDUCE_IMPL(T)                                                      \
  void dc_reduce_##T(T *dst, const T *src, uint64_t n, int op) {               \
    switch (op) {                                                              \
    case 0:                                                                    \
      for (uint64_t i = 0; i < n; ++i) dst[i] += src[i];                       \
      break;                                                                   \
    case 1:                                                                    \
      for (uint64_t i = 0; i < n; ++i) dst[i] = dst[i] > src[i] ? dst[i] : src[i]; \
      break;                                                                   \
    case 2:                                                                    \
      for (uint64_t i = 0; i < n; ++i) dst[i] = dst[i] < src[i] ? dst[i] : src[i]; \
      break;                                                                   \
    }                                                                          \
  }

DC_REDUCE_IMPL(float)
DC_REDUCE_IMPL(double)
DC_REDUCE_IMPL(int32_t)
DC_REDUCE_IMPL(int64_t)

} // extern "C"
