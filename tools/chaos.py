#!/usr/bin/env python
"""chaos — kill/promote and elastic-membership soak driver for AsyncEA.

Three entry points (docs/HA.md, docs/ELASTIC.md):

    python tools/chaos.py parity   --rounds 16 --kills 5,11 [--mid-flight]
    python tools/chaos.py churn    --rounds 12 --clients 3 --server-kills 2
    python tools/chaos.py scenario --name flash_join --rounds 12 --seed 0

``parity`` runs one client against a striped concurrent center with
checkpointing on, kills the center at the requested rounds (either on a
round boundary or genuinely mid-stripe-leg with ``--mid-flight``),
promotes a standby on a second port window each time, and asserts the
surviving fleet converges to BITWISE the same center and client params
as an unkilled S=1 reference run — plus zero leaked fds/threads and
clean obs counters (``async_ea_failover_*``, ``center_ckpt_*``).  The
client object is never restarted; recovery is ``AsyncEAClient.failover``
walking its dial list.

Why bitwise parity holds under any kill point: the client's flush-at-
top-of-sync raises BEFORE any param mutation, so its local trajectory
is kill-invariant; and the per-(cid, stripe) applied-seq ledger is
checkpointed in the same lock hold as the center slice it covers, so
the rejoin replay re-applies exactly the stripes the restored center is
missing — never zero, never twice (docs/HA.md).

``churn`` is the multi-client liveness soak (the ``slow``/``chaos``
marked tier-2 test): random-ish client self-kills mid-handshake plus
center kills under load; asserts every client finishes its rounds, one
promotion per center kill, and no fd/thread accumulation — not parity
(rejoin adopts the current center, deliberately forking the
trajectory).

``scenario`` is the elastic-fleet chaos driver (docs/ELASTIC.md): four
named, seeded scenarios over the comm-layer fault-injection plan
(``comm/faults.py``) and the elastic membership verbs —

* ``flash_join``     — the fleet doubles (2 -> 4 clients) mid-run via
  ``Join?`` and must still converge to the descent target within
  tolerance of a fixed 2-client reference run;
* ``rolling_leave``  — join two (one at double capacity), then leave
  them one at a time through the graceful ``Leave?`` flush; membership
  must return to the founding fleet with every leave accounted;
* ``slow_node``      — a seeded delay is injected on one client's
  dedicated link AFTER its latency floor is established; its
  straggler-adaptive τ must stretch above τ_lo (bounded by the α·τ
  product) while the fleet still converges;
* ``partition_heal`` — a one-way send partition lands exactly between
  the sync's param math and the delta push; the server evicts, the
  link heals, and the rejoin replay must land the blackholed delta
  EXACTLY once — asserted bitwise against the unkilled reference.

Four more scenarios drive the SERVING fleet (docs/SERVING.md): a
``serve.Router`` over shared-nothing ``ServeServer`` replicas —

* ``replica_kill``        — kill 1 of 3 replicas mid-wave; every
  accepted request must end in a terminal result (resubmitted to a
  survivor or a clean partial ``failed``), and the post-kill fleet
  keeps serving;
* ``slow_replica``        — a straggler replica stalls prefill; hedged
  requests must cancel there and complete on the healthy one;
* ``overload_shed``       — a saturated fleet refuses with RouterBusy +
  ``retry_after`` at both the router watermark and the replica's
  QueueFull, then admits again once drained;
* ``swap_during_traffic`` — an epoch-2 checkpoint lands under shared-
  prefix load with the radix prefix cache on; zero failed streams,
  zero fence violations, no stream observes two epochs, and the swap
  must invalidate the cache — post-swap repeats of pre-swap prompts
  are checked against a fresh epoch-2 reference (zero stale-KV
  streams).

Three TRAFFIC scenarios exercise the observability plane end-to-end
(docs/OBSERVABILITY.md) — realistic request mixes instead of injected
faults —

* ``zipf_mix``     — Zipf-popularity shared-prefix catalog (a system-
  prompt pool) over a 2-replica fleet with the radix prefix cache on;
  every repeat of a prompt must decode to the identical token stream
  whether its prefill came from compute or cached pages (greedy decode
  is a fleet-wide contract), cache hits and ``cached_tokens`` must
  surface, TTFT p95 must hold, and the obs counters must account for
  every request;
* ``diurnal``      — a one-day sine of wave sizes against one replica;
  the windowed TTFT-p95 SLO must breach at the peak and recover once
  the trough traffic leaves the window (``slo_breaches_total`` /
  ``slo_recoveries_total`` both fire);
* ``flash_crowd``  — a 10x request burst against a one-replica fleet;
  the obs-driven autoscaler (tools/autoscaler.py) must scale up on the
  TTFT breach, the burst must complete with the spawned replica taking
  real dispatches, and after the crowd passes the fleet must cool down
  and retire back to baseline.

Settle/recovery budgets honor ``DISTLEARN_CHAOS_SETTLE_S`` and
``DISTLEARN_CHAOS_RECOVER_S`` (seconds) for slow CI machines.

Importable: tests/test_chaos.py and tests/test_elastic.py drive
run_parity / run_churn / run_scenario directly.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import shutil
import socket
import sys
import tempfile
import threading
import time
from contextlib import closing

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distlearn_tpu.comm import FaultPlan, ProtocolError  # noqa: E402
from distlearn_tpu.obs import core  # noqa: E402
from distlearn_tpu.parallel import ha  # noqa: E402
from distlearn_tpu.parallel.async_ea import (  # noqa: E402
    ENTER, ENTER_Q, AsyncEAClient, AsyncEAServerConcurrent)

_SYNC_ERRORS = (OSError, TimeoutError, ProtocolError)

#: CI-tunable budgets: how long a fleet may take to drain in-flight legs
#: (settle) and how long a client may take to re-enter after a fault
#: (recover).  Loaded once at import; override via the environment.
CHAOS_SETTLE_S = float(os.environ.get("DISTLEARN_CHAOS_SETTLE_S", "30"))
CHAOS_RECOVER_S = float(os.environ.get("DISTLEARN_CHAOS_RECOVER_S", "120"))


def _reserve_window(n: int, host: str = "127.0.0.1") -> int:
    """A base port whose window ``base .. base+n-1`` was just bindable
    (tests/net_util.py idiom — tools must not import tests/)."""
    for _ in range(256):
        with closing(socket.socket()) as probe:
            probe.bind((host, 0))
            base = probe.getsockname()[1]
        if base + n >= 65535:
            continue
        socks = []
        try:
            try:
                for i in range(n):
                    s = socket.socket()
                    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                    s.bind((host, base + i))
                    socks.append(s)
            except OSError:
                continue
            return base
        finally:
            for s in socks:
                s.close()
    raise RuntimeError(f"could not reserve a {n}-port window")


_SHAPES = (("a", (64, 3)), ("b", (7,)), ("c", (32, 32)),
           ("d", (5,)), ("e", (128,)), ("f", (2, 2)))


def _params() -> dict:
    """Six float32 leaves, ragged shapes (mirrors the shard tests) —
    exercises sub-leaf striping at S=4."""
    rng = np.random.default_rng(0)
    return {k: rng.standard_normal(shape).astype(np.float32)
            for k, shape in _SHAPES}


def _target() -> dict:
    """The descent target for the elastic scenarios — a fixed point every
    client pulls toward, so 'did the varying fleet still converge' is a
    measurable distance, not a vibe."""
    rng = np.random.default_rng(1)
    return {k: rng.standard_normal(shape).astype(np.float32)
            for k, shape in _SHAPES}


def _drift(p: dict, r: int) -> dict:
    """Deterministic dyadic local 'training' step — exactly
    representable in float32, so parity can be asserted bitwise."""
    step = np.float32((r % 5) + 0.25)
    return {k: v + step for k, v in p.items()}


def _descend(p: dict, tgt: dict) -> dict:
    """One gradient step toward ``tgt`` (lr 0.25, dyadic): unlike
    ``_drift`` the fixed point is the same for ANY fleet size, so the
    elastic scenarios can assert distance-to-target against a
    fixed-fleet reference."""
    lr = np.float32(0.25)
    return {k: v - lr * (v - tgt[k]) for k, v in p.items()}


def _dist(center: list, tgt: dict) -> float:
    """Max per-leaf RMS distance between a center snapshot and the
    target (leaf order: sorted keys, matching the pytree flatten)."""
    worst = 0.0
    for leaf, key in zip(center, sorted(tgt)):
        want = tgt[key]
        if leaf.shape != want.shape:
            raise RuntimeError(
                f"leaf order drifted: {leaf.shape} vs {key}:{want.shape}")
        worst = max(worst, float(np.sqrt(np.mean((leaf - want) ** 2))))
    return worst


def _live(srv) -> int:
    return len(srv.members - srv.evicted)


def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def _totals(snap: list[dict]) -> dict:
    """Counter/gauge family name -> summed value across label sets."""
    out = {}
    for fam in snap:
        if fam["kind"] not in ("counter", "gauge"):
            continue
        out[fam["name"]] = sum(s.get("value", 0) for s in fam["samples"])
    return out


def _labeled(snap: list[dict], name: str) -> dict:
    for fam in snap:
        if fam["name"] == name:
            return {json.dumps(s["labels"], sort_keys=True): s["value"]
                    for s in fam["samples"]}
    return {}


def _quiet(srv) -> bool:
    with srv._lock:
        if srv._inflight:
            return False
    return (all(q.empty() for q in srv._queues.values())
            and all(q.empty() for q in srv._shard_queues.values()))


def _settle_fleet(clients, srv, timeout: float | None = None) -> None:
    """Block until every submitted delta is fully applied: overlap
    senders flushed, no leg in flight, sync count stable across two
    quiet polls."""
    for cl in clients:
        if cl._sender is not None:
            cl._sender.flush()
    deadline = time.monotonic() + (CHAOS_SETTLE_S if timeout is None
                                   else timeout)
    last = -1
    while time.monotonic() < deadline:
        if _quiet(srv):
            n = srv.syncs_completed
            if n == last:
                return
            last = n
        else:
            last = -1
        time.sleep(0.05)
    raise RuntimeError("fleet did not settle (legs still in flight)")


def _spawn_fleet(host, port, num_clients, shards, codecs, overlap,
                 centers, params, handshake_timeout=5.0,
                 rejoin_grace=60.0, elastic=False, tau=1, alpha=0.5,
                 adaptive_tau=False, server_centers=None):
    """Server + clients, concurrently (both constructors block on the
    accept/dial handshake).  Returns (server, [clients], [params]).
    ``server_centers`` is the HA roster the server advertises in Join
    ACKs so Join?-admitted clients can failover() too."""
    box: dict = {}

    def _dial(i):
        try:
            box[i] = AsyncEAClient(
                host, port, node=i + 1, tau=tau, alpha=alpha,
                codec=codecs[i % len(codecs)], overlap=overlap,
                centers=centers, adaptive_tau=adaptive_tau)
        except Exception as e:  # noqa: BLE001 — surfaced below
            box[i] = e

    threads = [threading.Thread(target=_dial, args=(i,), daemon=True)
               for i in range(num_clients)]
    for t in threads:
        t.start()
    srv = AsyncEAServerConcurrent(
        host, port, num_nodes=num_clients, shards=shards,
        accept_timeout=60.0, handshake_timeout=handshake_timeout,
        rejoin_grace=rejoin_grace, elastic=elastic,
        centers=server_centers)
    for t in threads:
        t.join(timeout=60.0)
    clients = []
    for i in range(num_clients):
        got = box.get(i)
        if not isinstance(got, AsyncEAClient):
            raise RuntimeError(f"client {i + 1} dial failed: {got!r}")
        clients.append(got)
    srv.init_server(params)
    ps = [cl.init_client(params) for cl in clients]
    srv.start()
    return srv, clients, ps


def _kill_and_promote(srv, host, new_port, params, ckpt_dir, shards,
                      ckpt_every, *, flush_first, stop_deadline=2.0,
                      handshake_timeout=5.0, rejoin_grace=60.0):
    """The failover event: (optionally checkpoint, then) kill the
    primary, construct a standby on the other port window, promote it
    from the checkpoint directory, start serving.  Returns the promoted
    server."""
    if flush_first:
        srv.checkpoint_now(wait=True)
    srv.stop(deadline=stop_deadline)
    srv.close()   # blocks on the async ckpt writer: promotion sees it
    standby = AsyncEAServerConcurrent(
        host, new_port, num_nodes=srv.num_nodes, shards=shards,
        handshake_timeout=handshake_timeout, rejoin_grace=rejoin_grace,
        standby=True)
    ha.promote(standby, ckpt_dir, params)
    standby.enable_checkpoint(ckpt_dir, every=ckpt_every)
    standby.start()
    return standby


def _sync_with_failover(cl, p, attempts: int = 100):
    """One round's sync, retried through ``failover`` until it lands.
    The drift for the round happened OUTSIDE this loop, so a retry
    replays the same local state."""
    last = None
    for _ in range(attempts):
        try:
            p2, _ = cl.sync_client(p)
            return p2
        except _SYNC_ERRORS as e:
            last = e
            cl.failover(p, retries=40, retry_interval=0.01,
                        handshake_timeout=15.0)
    raise RuntimeError(f"sync never succeeded after failover: {last!r}")


def _leaves_of(srv) -> list[np.ndarray]:
    return [np.asarray(t) for t in srv._snapshot()]


def _teardown(clients, srv):
    for cl in clients:
        try:
            cl.close()
        except (OSError, RuntimeError):
            pass
    srv.stop(deadline=5.0)
    srv.close()


def _settle_leaks(fd_base: int, th_base: int, timeout: float = 10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if _fd_count() <= fd_base and threading.active_count() <= th_base:
            break
        time.sleep(0.1)
    return _fd_count(), threading.active_count()


def _run_reference(host: str, rounds: int, overlap: bool) -> tuple:
    """Unkilled S=1 raw-wire run — the parity oracle."""
    port = _reserve_window(4, host)
    base = _params()
    srv, (cl,), (p,) = _spawn_fleet(host, port, 1, 1, ["raw"], overlap,
                                    None, base)
    for r in range(rounds):
        p = _drift(p, r)
        p, _ = cl.sync_client(p)
    _settle_fleet([cl], srv)
    center = _leaves_of(srv)
    _teardown([cl], srv)
    return p, center


def run_parity(rounds: int = 16, kills=(6,), shards: int = 4,
               overlap: bool = True, ckpt_every: int = 1,
               mid_flight: bool = False, host: str = "127.0.0.1") -> dict:
    """Kill/promote soak asserting bitwise convergence-to-parity.

    ``kills``: rounds at which the center dies.  Boundary mode kills
    between rounds (checkpoint flushed first); ``mid_flight`` kills
    while the kill-round's delta is on the wire, so recovery leans on
    the rejoin replay instead of the checkpoint alone.
    """
    kills = sorted(set(int(k) for k in kills))
    if kills and (kills[0] < 1 or kills[-1] >= rounds):
        raise ValueError("kill rounds must fall inside 1..rounds-1")
    core.configure(True)
    core.REGISTRY.reset()
    tmp = tempfile.mkdtemp(prefix="chaos-ckpt-")
    try:
        ref_p, ref_center = _run_reference(host, rounds, overlap)
        fd_base, th_base = _fd_count(), threading.active_count()

        windows = [_reserve_window(8, host), _reserve_window(8, host)]
        win = 0
        base = _params()
        srv, (cl,), (p,) = _spawn_fleet(
            host, windows[0], 1, shards, ["raw"], overlap,
            [(host, windows[1])], base)
        srv.enable_checkpoint(tmp, every=ckpt_every)
        killset = set(kills)
        for r in range(rounds):
            if r in killset:
                _settle_fleet([cl], srv)
                if mid_flight:
                    # prior rounds durable; the kill-round delta itself
                    # is covered by the ledger + rejoin replay
                    srv.checkpoint_now(wait=True)
                    p = _drift(p, r)
                    p, _ = cl.sync_client(p)
                    win = 1 - win
                    srv = _kill_and_promote(
                        srv, host, windows[win], base, tmp, shards,
                        ckpt_every, flush_first=False, stop_deadline=0.25)
                    continue
                win = 1 - win
                srv = _kill_and_promote(
                    srv, host, windows[win], base, tmp, shards,
                    ckpt_every, flush_first=True)
            p = _drift(p, r)
            p = _sync_with_failover(cl, p)
        _settle_fleet([cl], srv)
        center = _leaves_of(srv)
        _teardown([cl], srv)
        fd_end, th_end = _settle_leaks(fd_base, th_base)
        snap = core.REGISTRY.snapshot()

        totals = _totals(snap)
        failures = []
        for i, (a, b) in enumerate(zip(ref_center, center)):
            if a.dtype != b.dtype or not np.array_equal(a, b):
                failures.append(f"center leaf {i} diverged "
                                f"(max |d|={np.abs(a - b).max()})")
        for k in ref_p:
            if not np.array_equal(ref_p[k], p[k]):
                failures.append(f"client param {k!r} diverged")
        n_kills = len(kills)
        checks = [
            ("promotions", totals.get(
                "async_ea_failover_promotions_total", 0), n_kills),
            ("ckpt_restores", totals.get(
                "center_ckpt_restores_total", 0), n_kills),
            ("stale_refusals", totals.get(
                "async_ea_failover_stale_refusals_total", 0), 0),
        ]
        for name, got, want in checks:
            if got != want:
                failures.append(f"{name}: got {got}, want {want}")
        if totals.get("async_ea_failover_redials_total", 0) < n_kills:
            failures.append("fewer re-dials than kills")
        if totals.get("center_ckpt_saves_total", 0) < 1:
            failures.append("no checkpoints were saved")
        if totals.get("async_ea_server_threads", 0) != 0:
            failures.append("server thread gauge nonzero after stop")
        if totals.get("async_ea_inflight", 0) != 0:
            failures.append("inflight gauge nonzero after stop")
        if fd_end > fd_base + 2:
            failures.append(f"fd leak: {fd_base} -> {fd_end}")
        if th_end > th_base:
            failures.append(f"thread leak: {th_base} -> {th_end}")

        report = {
            "scenario": "parity",
            "rounds": rounds, "kills": kills, "shards": shards,
            "overlap": overlap, "mid_flight": mid_flight,
            "promotions": totals.get(
                "async_ea_failover_promotions_total", 0),
            "redials": totals.get("async_ea_failover_redials_total", 0),
            "replays": _labeled(snap,
                                "async_ea_failover_replays_total"),
            "ckpt_saves": totals.get("center_ckpt_saves_total", 0),
            "fds": [fd_base, fd_end], "threads": [th_base, th_end],
            "failures": failures,
        }
        if failures:
            raise AssertionError("chaos parity failed: "
                                 + "; ".join(failures)
                                 + f"\n{json.dumps(report, indent=2)}")
        return report
    finally:
        core.REGISTRY.reset()
        core.configure(None)
        shutil.rmtree(tmp, ignore_errors=True)


def _client_self_kill(cl):
    """Die mid-handshake: announce Enter?, then vanish.  The center's
    handshake deadline evicts the cid; the same client object later
    recovers through rejoin/failover — no restart."""
    try:
        cl._announce(ENTER_Q, ENTER)
    except Exception:  # noqa: BLE001 — dying is the point
        pass
    for c in (cl.broadcast, cl.conn, *cl._shard_conns):
        try:
            c.close()
        except OSError:
            pass


def _recover(cl, p, deadline_s: float | None = None):
    """Post-self-kill recovery loop: rejoin the current center (must
    wait out our own eviction), falling back to the failover dial walk
    when the center itself died meanwhile."""
    deadline = time.monotonic() + (CHAOS_RECOVER_S if deadline_s is None
                                   else deadline_s)
    while time.monotonic() < deadline:
        try:
            return cl.rejoin(p, retries=5, retry_interval=0.02,
                             handshake_timeout=5.0)
        except _SYNC_ERRORS:
            time.sleep(0.05)
        try:
            return cl.failover(p, retries=10, retry_interval=0.02,
                               handshake_timeout=5.0)
        except _SYNC_ERRORS:
            time.sleep(0.05)
    raise RuntimeError(f"client {cl.node} could not recover")


def run_churn(rounds: int = 12, num_clients: int = 3, shards: int = 4,
              overlap: bool = True, server_kills: int = 2,
              ckpt_every: int = 1, host: str = "127.0.0.1") -> dict:
    """Multi-client liveness soak: every client self-kills once
    (mid-handshake), the center dies ``server_kills`` times under load.
    Asserts liveness + counter sanity + zero leaks, NOT parity."""
    core.configure(True)
    core.REGISTRY.reset()
    tmp = tempfile.mkdtemp(prefix="chaos-churn-")
    fd_base, th_base = _fd_count(), threading.active_count()
    try:
        nports = num_clients + 2 + max(0, shards - 1)
        windows = [_reserve_window(nports, host),
                   _reserve_window(nports, host)]
        base = _params()
        codecs = ["raw", "int8", "fp16"]   # mixed fleet
        srv, clients, ps = _spawn_fleet(
            host, windows[0], num_clients, shards, codecs, overlap,
            [(host, windows[1])], base,
            handshake_timeout=2.0, rejoin_grace=120.0)
        srv.enable_checkpoint(tmp, every=ckpt_every)

        errors: dict = {}
        done = threading.Event()

        def _drive(i, cl, p):
            kill_round = 2 + (i % max(1, rounds - 3))
            try:
                for r in range(rounds):
                    if r == kill_round:
                        _client_self_kill(cl)
                        p = _recover(cl, p)
                    p = _drift(p, r)
                    p = _sync_with_failover(cl, p)
            except Exception as e:  # noqa: BLE001 — reported below
                errors[i] = e

        threads = [threading.Thread(target=_drive, args=(i, cl, p),
                                    daemon=True)
                   for i, (cl, p) in enumerate(zip(clients, ps))]
        for t in threads:
            t.start()

        # center kills from the main thread, spread across the run
        win, kills_done = 0, 0
        total = rounds * num_clients
        srv_box = [srv]
        while any(t.is_alive() for t in threads):
            if (kills_done < server_kills
                    and srv_box[0].syncs_completed
                    >= (kills_done + 1) * total // (server_kills + 1)):
                win = 1 - win
                srv_box[0] = _kill_and_promote(
                    srv_box[0], host, windows[win], base, tmp, shards,
                    ckpt_every, flush_first=True, stop_deadline=2.0,
                    handshake_timeout=2.0, rejoin_grace=120.0)
                kills_done += 1
            time.sleep(0.05)
        done.set()
        for t in threads:
            t.join(timeout=60.0)

        _teardown(clients, srv_box[0])
        fd_end, th_end = _settle_leaks(fd_base, th_base)
        snap = core.REGISTRY.snapshot()

        totals = _totals(snap)
        failures = [f"client {i + 1} died: {e!r}"
                    for i, e in sorted(errors.items())]
        if any(t.is_alive() for t in threads):
            failures.append("client threads still alive (liveness)")
        if totals.get("async_ea_failover_promotions_total",
                      0) != kills_done:
            failures.append("promotions != server kills")
        if totals.get("async_ea_evictions_total", 0) < num_clients:
            failures.append("fewer evictions than client self-kills")
        if totals.get("async_ea_rejoins_total", 0) < num_clients:
            failures.append("fewer rejoins than client self-kills")
        if totals.get("async_ea_server_threads", 0) != 0:
            failures.append("server thread gauge nonzero after stop")
        if totals.get("async_ea_inflight", 0) != 0:
            failures.append("inflight gauge nonzero after stop")
        if fd_end > fd_base + 2:
            failures.append(f"fd leak: {fd_base} -> {fd_end}")
        if th_end > th_base:
            failures.append(f"thread leak: {th_base} -> {th_end}")

        report = {
            "scenario": "churn",
            "rounds": rounds, "clients": num_clients, "shards": shards,
            "server_kills": kills_done,
            "promotions": totals.get(
                "async_ea_failover_promotions_total", 0),
            "evictions": totals.get("async_ea_evictions_total", 0),
            "rejoins": totals.get("async_ea_rejoins_total", 0),
            "redials": totals.get("async_ea_failover_redials_total", 0),
            "replays": _labeled(snap,
                                "async_ea_failover_replays_total"),
            "fds": [fd_base, fd_end], "threads": [th_base, th_end],
            "failures": failures,
        }
        if failures:
            raise AssertionError("chaos churn failed: "
                                 + "; ".join(failures)
                                 + f"\n{json.dumps(report, indent=2)}")
        return report
    finally:
        core.REGISTRY.reset()
        core.configure(None)
        shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# Elastic-fleet scenario driver (docs/ELASTIC.md).

def _run_descend_reference(host, steps, *, num_clients=2, tau=1,
                           alpha=0.5, adaptive_tau=False) -> float:
    """Fixed-fleet oracle for the elastic scenarios: the same descent
    dynamics with membership held constant and no faults.  Returns the
    settled center's distance to the target."""
    port = _reserve_window(num_clients + 3, host)
    tgt = _target()
    srv, clients, ps = _spawn_fleet(
        host, port, num_clients, 1, ["raw"], False, None, _params(),
        tau=tau, alpha=alpha, adaptive_tau=adaptive_tau)
    try:
        for _s in range(steps):
            for i, cl in enumerate(clients):
                ps[i] = _descend(ps[i], tgt)
                ps[i], _ = cl.sync_client(ps[i])
        _settle_fleet(clients, srv)
        return _dist(_leaves_of(srv), tgt)
    finally:
        _teardown(clients, srv)


def _drive_round(clients, ps, tgt):
    for i, cl in enumerate(clients):
        ps[i] = _descend(ps[i], tgt)
        ps[i], _ = cl.sync_client(ps[i])


def _scenario_flash_join(rounds, seed, host):
    """The fleet doubles mid-run: 2 founding clients, 2 more flash-join
    at rounds//3 and stay.  Peak membership must hit 2x and the settled
    center must land within tolerance of the fixed 2-client oracle."""
    del seed  # no faults injected — determinism comes from the dynamics
    tgt = _target()
    ref = _run_descend_reference(host, rounds)
    port = _reserve_window(5, host)
    srv, clients, ps = _spawn_fleet(host, port, 2, 1, ["raw"], False,
                                    None, _params(), elastic=True)
    peak = _live(srv)
    try:
        for r in range(rounds):
            if r == max(1, rounds // 3):
                for _ in range(2):
                    cl, pj = AsyncEAClient.join(host, port, _params(),
                                                1, 0.5, sharded=False)
                    clients.append(cl)
                    ps.append(pj)
            _drive_round(clients, ps, tgt)
            peak = max(peak, _live(srv))
        _settle_fleet(clients, srv)
        dist = _dist(_leaves_of(srv), tgt)
    finally:
        _teardown(clients, srv)
    totals = _totals(core.REGISTRY.snapshot())
    tol = max(4.0 * ref, 1e-3)
    failures = []
    if peak != 4:
        failures.append(f"peak membership {peak}, want 4 (2x fleet)")
    if totals.get("async_ea_membership_joins_total", 0) != 2:
        failures.append("join counter != 2")
    if dist > tol:
        failures.append(f"did not converge: dist {dist:.4g} > tol "
                        f"{tol:.4g} (reference {ref:.4g})")
    return {"peak_members": peak, "dist": dist, "ref_dist": ref,
            "tol": tol}, failures


def _scenario_rolling_leave(rounds, seed, host):
    """Join two clients (one at double capacity — the capacity-weighted
    averaging path), then leave them one at a time through the graceful
    ``Leave?`` flush.  Membership must return to the founding 2 with
    every leave accounted, and convergence must hold throughout."""
    del seed
    tgt = _target()
    ref = _run_descend_reference(host, rounds)
    port = _reserve_window(5, host)
    srv, clients, ps = _spawn_fleet(host, port, 2, 1, ["raw"], False,
                                    None, _params(), elastic=True)
    joined: list = []
    peak = _live(srv)
    leave_at = sorted({max(3, rounds // 2), max(4, (3 * rounds) // 4)})
    try:
        for r in range(rounds):
            if r == 1:
                for capacity in (1.0, 2.0):
                    cl, pj = AsyncEAClient.join(
                        host, port, _params(), 1, 0.5,
                        capacity=capacity, sharded=False)
                    clients.append(cl)
                    ps.append(pj)
                    joined.append(cl)
            if r in leave_at and joined:
                cl = joined.pop()
                i = clients.index(cl)
                cl.leave()
                clients.pop(i)
                ps.pop(i)
            _drive_round(clients, ps, tgt)
            peak = max(peak, _live(srv))
        _settle_fleet(clients, srv)
        dist = _dist(_leaves_of(srv), tgt)
        final_live = _live(srv)
    finally:
        _teardown(clients, srv)
    totals = _totals(core.REGISTRY.snapshot())
    tol = max(4.0 * ref, 1e-3)
    failures = []
    if peak != 4:
        failures.append(f"peak membership {peak}, want 4 (2x fleet)")
    if final_live != 2:
        failures.append(f"final membership {final_live}, want the "
                        "founding 2")
    if totals.get("async_ea_membership_joins_total", 0) != 2:
        failures.append("join counter != 2")
    if totals.get("async_ea_membership_leaves_total", 0) != 2:
        failures.append("leave counter != 2")
    if dist > tol:
        failures.append(f"did not converge: dist {dist:.4g} > tol "
                        f"{tol:.4g} (reference {ref:.4g})")
    return {"peak_members": peak, "final_members": final_live,
            "dist": dist, "ref_dist": ref, "tol": tol}, failures


def _scenario_slow_node(rounds, seed, host):
    """Straggler-adaptive τ under an injected link delay: both clients
    run ``adaptive_tau`` at (τ=2, α=0.1); after the latency floor is
    established, a seeded delay lands on one client's dedicated link.
    Its effective τ must stretch above τ_lo without crossing the α·τ
    stability bound τ_hi, and the fleet must still converge."""
    tgt = _target()
    steps = rounds * 2
    ref = _run_descend_reference(host, steps, tau=2, alpha=0.1,
                                 adaptive_tau=True)
    port = _reserve_window(5, host)
    srv, clients, ps = _spawn_fleet(
        host, port, 2, 1, ["raw"], False, None, _params(),
        tau=2, alpha=0.1, adaptive_tau=True)
    plan = FaultPlan(seed)
    slow = clients[1]
    plan.wrap(slow.conn, "slow")
    warm = max(4, steps // 3)
    try:
        for s in range(steps):
            if s == warm:
                # only now: the τ controller must stretch from an
                # OBSERVED floor, not from a never-fast baseline
                plan.delay("slow", 0.05)
            _drive_round(clients, ps, tgt)
        plan.heal("slow")
        _settle_fleet(clients, srv)
        dist = _dist(_leaves_of(srv), tgt)
        tau_slow = slow.tau_effective
        tau_fast = clients[0].tau_effective
        lo, hi = slow._tau_lo, slow._tau_hi
    finally:
        _teardown(clients, srv)
    tol = max(6.0 * ref, 5e-2)
    failures = []
    if tau_slow <= lo:
        failures.append(f"adaptive tau never stretched: {tau_slow} <= "
                        f"tau_lo {lo} despite the injected delay")
    if tau_slow > hi:
        failures.append(f"adaptive tau {tau_slow} crossed the "
                        f"alpha*tau stability bound {hi}")
    if tau_fast > lo:
        failures.append(f"fast client stretched to {tau_fast} with no "
                        "fault on its link")
    if dist > tol:
        failures.append(f"did not converge: dist {dist:.4g} > tol "
                        f"{tol:.4g} (reference {ref:.4g})")
    return {"tau_slow": tau_slow, "tau_fast": tau_fast,
            "tau_bounds": [lo, hi], "dist": dist, "ref_dist": ref,
            "tol": tol, "fault_log": len(plan.decisions())}, failures


def _scenario_partition_heal(rounds, seed, host):
    """One-way send partition landing EXACTLY between a sync's param
    math and its delta push (the overlap sender's submit hook): the
    blackholed delta 'succeeds' client-side, the server's handshake
    timeout evicts the cid without applying it, the link heals, and the
    rejoin replay must land the pending delta exactly once — asserted
    BITWISE against the unkilled reference run (same guarantee the
    parity soak proves for kill/promote, here for partition/heal)."""
    ref_p, ref_center = _run_reference(host, rounds, overlap=True)
    port = _reserve_window(4, host)
    base = _params()
    srv, (cl,), (p,) = _spawn_fleet(host, port, 1, 1, ["raw"], True,
                                    None, base)
    plan = FaultPlan(seed)
    plan.wrap(cl.conn, "c1")
    k = max(1, rounds // 2)
    failures = []
    try:
        for r in range(rounds):
            p = _drift(p, r)
            if r == k:
                orig = cl._sender.submit

                def _cut(job, _orig=orig):
                    plan.partition("c1", "send")
                    return _orig(job)

                cl._sender.submit = _cut
                p, _ = cl.sync_client(p)
                cl._sender.submit = orig
                # the push is blackholed mid-handshake; the server's
                # handshake timeout must evict without applying seq k
                deadline = time.monotonic() + CHAOS_RECOVER_S
                while (cl.node not in srv.evicted
                       and time.monotonic() < deadline):
                    time.sleep(0.02)
                if cl.node not in srv.evicted:
                    failures.append("server never evicted the "
                                    "partitioned client")
                plan.heal("c1")
            else:
                p = _sync_with_failover(cl, p)
        _settle_fleet([cl], srv)
        center = _leaves_of(srv)
    finally:
        _teardown([cl], srv)
    totals = _totals(core.REGISTRY.snapshot())
    dropped = plan.dropped_bytes("c1")
    for i, (a, b) in enumerate(zip(ref_center, center)):
        if a.dtype != b.dtype or not np.array_equal(a, b):
            failures.append(f"center leaf {i} diverged "
                            f"(max |d|={np.abs(a - b).max()})")
    for key in ref_p:
        if not np.array_equal(ref_p[key], p[key]):
            failures.append(f"client param {key!r} diverged")
    if dropped <= 0:
        failures.append("partition blackholed no bytes — the fault "
                        "never landed on the delta push")
    if totals.get("async_ea_evictions_total", 0) < 1:
        failures.append("no eviction recorded")
    if totals.get("async_ea_rejoins_total", 0) < 1:
        failures.append("no rejoin recorded — the replay path never ran")
    return {"partition_round": k, "dropped_bytes": dropped,
            "evictions": totals.get("async_ea_evictions_total", 0),
            "rejoins": totals.get("async_ea_rejoins_total", 0)}, failures


# ---------------------------------------------------------------------------
# Serving-fleet scenario driver (docs/SERVING.md): a Router over N
# shared-nothing ServeServer replicas under client load while faults land.

_SERVE_LM = {"vocab": 61, "dim": 32, "depth": 2, "heads": 4, "max_len": 64}


def _lm_params():
    import jax
    from distlearn_tpu.models.transformer import transformer_lm
    model = transformer_lm(**_SERVE_LM)
    params, _ = model.init(jax.random.PRNGKey(0))
    return params


def _serve_prompts(n, seed):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, _SERVE_LM["vocab"],
                         size=int(rng.integers(3, 9))).astype(np.int32)
            for _ in range(n)]


def _prefixed_prompts(n, seed, *, pool=3, prefix_len=24):
    """Shared-prefix catalog: each prompt is a 'system prompt' drawn
    from a small pool (page-aligned length, so the radix prefix cache
    can retain it) plus a short unique suffix — the traffic shape the
    prefix cache exists for."""
    rng = np.random.default_rng(seed)
    prefixes = [rng.integers(1, _SERVE_LM["vocab"],
                             size=prefix_len).astype(np.int32)
                for _ in range(pool)]
    out = []
    for i in range(n):
        sfx = rng.integers(1, _SERVE_LM["vocab"],
                           size=int(rng.integers(4, 9))).astype(np.int32)
        out.append(np.concatenate([prefixes[i % pool], sfx]))
    return out


def _hist_sample(snap, name):
    for fam in snap:
        if fam["name"] == name and fam["samples"]:
            return fam["samples"][0]
    return None


def _hist_p95(sample, base=None):
    """p95 upper bound from a snapshot histogram (the smallest bucket
    edge covering 95% of observations; inf when the tail spilled past
    the last bound).  ``base`` subtracts an earlier snapshot so warmup
    samples (jit compiles) don't pollute the steady-state quantile."""
    if sample is None:
        return None
    buckets = dict(sample["buckets"])
    count = sample["count"]
    if base is not None:
        for k in buckets:
            buckets[k] -= base["buckets"].get(k, 0)
        count -= base["count"]
    if count <= 0:
        return None
    need = math.ceil(0.95 * count)
    cum = 0
    for b in sorted(float(k) for k in buckets):
        cum += buckets[str(b)]
        if cum >= need:
            return b
    return float("inf")


def _spawn_replicas(host, port, n, params, *, num_slots=2, **server_kw):
    """N independent single-process replicas on consecutive ports, each
    with its own engine and KV cache (shared-nothing, like the real
    fleet — only the checkpoint directory may be shared)."""
    from distlearn_tpu.serve.engine import DecodeEngine
    from distlearn_tpu.serve.server import ServeServer
    servers = []
    for i in range(n):
        eng = DecodeEngine(params, num_slots=num_slots,
                           max_len=_SERVE_LM["max_len"], page=8)
        servers.append(ServeServer(eng, host=host, port=port + i,
                                   idle_wait=0.005, **server_kw).start())
    return servers


def _stop_replicas(servers):
    for srv in servers:
        try:
            srv.stop()
        except OSError:
            pass


def _fleet_load(router, prompts, max_new, *, stagger=0.0, timeout=None,
                on_index=None):
    """One ``router.generate`` per prompt from worker threads (launch
    staggered from the driver thread), collecting a result-or-exception
    per request.  ``on_index(i)`` runs in the driver thread just before
    request ``i`` launches — the scenario's fault hook.  Returns
    ``(results, hung)`` where ``hung`` counts threads that outlived the
    recovery budget (always a failure)."""
    timeout = CHAOS_RECOVER_S if timeout is None else timeout
    out: list = [None] * len(prompts)

    def _one(i):
        try:
            out[i] = router.generate(prompts[i], max_new, rid=f"q{i}",
                                     timeout=timeout)
        except Exception as e:  # noqa: BLE001 — classified by the caller
            out[i] = e

    threads = []
    for i in range(len(prompts)):
        if on_index is not None:
            on_index(i)
        t = threading.Thread(target=_one, args=(i,), daemon=True)
        t.start()
        threads.append(t)
        if stagger:
            time.sleep(stagger)
    for t in threads:
        t.join(timeout=CHAOS_RECOVER_S)
    return out, sum(1 for t in threads if t.is_alive())


def _scenario_replica_kill(rounds, seed, host):
    """Kill 1 of 3 replicas under a staggered request wave.  Every
    accepted request must end in a terminal result: queued-not-yet-
    prefilled requests resubmitted to survivors (``router_retries_total``),
    mid-stream deaths surfaced as clean ``reason="failed"`` with the
    partial tokens — never a hang or an unclassified error.  The
    post-kill fleet must keep completing fresh requests on the two
    survivors."""
    from distlearn_tpu.serve.router import Router
    params = _lm_params()
    port = _reserve_window(3, host)
    servers = _spawn_replicas(host, port, 3, params)
    total = rounds * 3
    kill_at = total // 2
    try:
        with Router([(host, port + i) for i in range(3)],
                    health_ttl=0.05, retry_interval=0.02,
                    dial_deadline=1.0) as router:

            def _fault(i):
                if i == kill_at:
                    servers[0].stop()       # hard kill: sockets cut

            results, hung = _fleet_load(
                router, _serve_prompts(total, seed), 4,
                stagger=0.02, on_index=_fault)
            post, hung_post = _fleet_load(
                router, _serve_prompts(6, seed + 1), 4)
    finally:
        _stop_replicas(servers)
    snap = core.REGISTRY.snapshot()
    retries = sum(_labeled(snap, "router_retries_total").values())
    dispatched = _labeled(snap, "router_dispatch_total")
    done = [r for r in results
            if isinstance(r, dict) and r["reason"] in ("complete", "eos")]
    failed = [r for r in results
              if isinstance(r, dict) and r["reason"] == "failed"]
    errs = [r for r in results if not isinstance(r, dict)]
    failures = []
    if hung or hung_post:
        failures.append(f"{hung + hung_post} request thread(s) hung past "
                        "the recovery budget")
    if errs:
        failures.append(f"{len(errs)} request(s) raised instead of ending "
                        f"in a terminal result: {errs[:3]!r}")
    if len(done) + len(failed) != total:
        failures.append(f"terminal results {len(done)}+{len(failed)} != "
                        f"accepted {total}")
    if any(len(r["tokens"]) != 4 for r in done):
        failures.append("a completed stream delivered a short token count")
    if retries + len(failed) < 1:
        failures.append("the kill was never observed: no resubmission and "
                        "no mid-stream failure")
    if len(dispatched) < 2:
        failures.append("load never spread past one replica")
    bad_post = [r for r in post
                if not (isinstance(r, dict) and r["reason"] == "complete")]
    if bad_post:
        failures.append(f"post-kill fleet dropped {len(bad_post)} of "
                        f"{len(post)} fresh requests: {bad_post[:3]!r}")
    return {"requests": total, "completed": len(done),
            "failed_mid_stream": len(failed), "retries": retries,
            "replicas_dispatched": len(dispatched)}, failures


def _scenario_slow_replica(rounds, seed, host):
    """One of two replicas turns straggler: its prefill path sleeps 0.4s
    (a replica wedged on compilation/paging — alive, answering probes,
    producing nothing).  With deadline-aware hedging armed at 0.1s,
    requests stuck behind it with no first token must cancel there and
    re-dispatch: every request completes and ``router_hedges_total``
    fires.  At-most-once holds — the canceled copy decodes into a
    closed socket, never into the client."""
    from distlearn_tpu.serve.router import Router
    params = _lm_params()
    port = _reserve_window(2, host)
    servers = _spawn_replicas(host, port, 2, params)
    slow = servers[0]                       # list head wins score ties
    orig_admit = slow.engine.admit

    def _slow_admit(*a, **kw):
        time.sleep(0.4)
        return orig_admit(*a, **kw)

    slow.engine.admit = _slow_admit
    try:
        with Router([(host, port), (host, port + 1)], health_ttl=0.02,
                    hedge_after=0.1, retry_interval=0.02,
                    dial_deadline=1.0) as router:
            results, hung = _fleet_load(
                router, _serve_prompts(rounds, seed), 4, stagger=0.05)
    finally:
        _stop_replicas(servers)
    snap = core.REGISTRY.snapshot()
    hedges = sum(_labeled(snap, "router_hedges_total").values())
    done = [r for r in results
            if isinstance(r, dict) and r["reason"] == "complete"]
    failures = []
    if hung:
        failures.append(f"{hung} request thread(s) hung")
    if len(done) != rounds:
        bad = [r for r in results if r not in done]
        failures.append(f"only {len(done)}/{rounds} completed: "
                        f"{bad[:3]!r}")
    if hedges < 1:
        failures.append("no hedge fired despite the straggler")
    fast = f"{host}:{port + 1}"
    if not any(r.get("replica") == fast for r in done):
        failures.append("no completion landed on the healthy replica")
    return {"requests": rounds, "completed": len(done),
            "hedges": hedges}, failures


def _scenario_overload_shed(rounds, seed, host):
    """Saturate a one-replica fleet with a long slow decode.  Router
    admission control must refuse new work with ``RouterBusy`` carrying
    a ``retry_after`` hint (graceful degradation, not a client-side
    timeout); the replica's own ``QueueFull`` shed must surface through
    a watermark-less router as RouterBusy too; and once the backlog
    drains the same fleet must accept work again."""
    from distlearn_tpu.serve.router import Router, RouterBusy
    params = _lm_params()
    port = _reserve_window(1, host)
    (srv,) = _spawn_replicas(host, port, 1, params, num_slots=1,
                             max_queue=1)
    orig_tick = srv.engine.tick

    def _slow_tick(*a, **kw):
        time.sleep(0.05)                    # ~2.4s for the 48-token run
        return orig_tick(*a, **kw)

    srv.engine.tick = _slow_tick
    prompts = _serve_prompts(3, seed)
    failures: list = []
    box: dict = {}
    try:
        with Router([(host, port)], shed_watermark=1, health_ttl=0.01,
                    dial_deadline=1.0) as router, \
             Router([(host, port)], shed_watermark=None, health_ttl=0.01,
                    dial_deadline=1.0) as bare:

            def _bg(key, rtr, prompt, max_new):
                def _run():
                    try:
                        box[key] = rtr.generate(prompt, max_new, rid=key,
                                                timeout=CHAOS_RECOVER_S)
                    except Exception as e:  # noqa: BLE001
                        box[key] = e
                t = threading.Thread(target=_run, daemon=True)
                t.start()
                return t

            t_long = _bg("long", router, prompts[0], 48)
            deadline = time.monotonic() + CHAOS_SETTLE_S
            while time.monotonic() < deadline:
                h = router.health()
                if h["queue_depth"] + h["active"] >= 1:
                    break
                time.sleep(0.01)
            else:
                failures.append("the long request never showed up in "
                                "fleet health")
            # router-level shed: aggregate depth is at the watermark
            sheds = hint = 0
            for i in range(rounds):
                try:
                    router.generate(prompts[1], 4, rid=f"shed{i}",
                                    timeout=5.0)
                    failures.append("a request was admitted past the "
                                    "watermark")
                except RouterBusy as e:
                    sheds += 1
                    hint = e.retry_after
                    if not e.retry_after or e.retry_after <= 0:
                        failures.append("RouterBusy without a retry_after "
                                        "hint")
            # replica-level shed: fill the depth-1 queue, then the next
            # submit gets the QueueFull rejection chunk and the
            # watermark-less router re-raises it as "every replica shed"
            t_fill = _bg("fill", bare, prompts[2], 4)
            deadline = time.monotonic() + CHAOS_SETTLE_S
            while (srv.sched.queue_depth() < 1
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            try:
                bare.generate(prompts[1], 4, rid="reject", timeout=5.0)
                failures.append("the replica's QueueFull never surfaced")
            except RouterBusy as e:
                if not e.retry_after:
                    failures.append("replica shed lost its retry_after "
                                    "hint through the router")
            t_long.join(CHAOS_RECOVER_S)
            t_fill.join(CHAOS_RECOVER_S)
            for key, want in (("long", 48), ("fill", 4)):
                got = box.get(key)
                if not (isinstance(got, dict)
                        and got["reason"] == "complete"
                        and len(got["tokens"]) == want):
                    failures.append(f"backlogged request {key!r} did not "
                                    f"complete: {got!r}")
            # drained fleet must admit again
            try:
                router.generate(prompts[1], 4, rid="after", timeout=30.0)
            except Exception as e:  # noqa: BLE001
                failures.append(f"post-drain request failed: {e!r}")
    finally:
        _stop_replicas([srv])
    totals = _totals(core.REGISTRY.snapshot())
    if totals.get("router_shed_total", 0) < sheds + 1:
        failures.append("router_shed_total undercounts the sheds")
    return {"sheds": sheds, "retry_after_hint": hint,
            "shed_total": totals.get("router_shed_total", 0)}, failures


def _scenario_swap_during_traffic(rounds, seed, host):
    """Epoch-fenced hot weight swap under live SHARED-PREFIX traffic
    with the radix prefix cache on: both replicas tail one checkpoint
    directory; a new center (epoch 2) lands mid-wave while cached
    KV pages from epoch-1 prefills are live in both caches.  The fence
    must hold — zero failed streams, zero fence violations, every
    stream pinned to exactly one epoch (the 'R'-chunk echo), both
    replicas converging to epoch 2 — AND the swap must invalidate the
    prefix cache: post-swap repeats of pre-swap catalog prompts are
    decoded against a fresh epoch-2 reference engine, so a single
    stale epoch-1 KV page surviving the fence shows up as a diverged
    stream (zero tolerated)."""
    from distlearn_tpu.models.transformer import greedy_generate
    from distlearn_tpu.serve.router import Router
    from distlearn_tpu.utils.checkpoint import save_checkpoint
    params = _lm_params()
    port = _reserve_window(2, host)
    ckpt_dir = tempfile.mkdtemp(prefix="chaos-swap-")
    servers = _spawn_replicas(host, port, 2, params, ckpt_dir=ckpt_dir,
                              ckpt_poll=0.02, epoch=1, prefix_cache=True)
    total = rounds * 2
    swap_at = total // 3
    catalog = _prefixed_prompts(6, seed)
    next_params = {}
    failures: list = []
    try:
        import jax
        next_params = jax.tree_util.tree_map(
            lambda a: np.asarray(a) * np.float32(0.5), params)
        with Router([(host, port), (host, port + 1)], health_ttl=0.02,
                    dial_deadline=1.0) as router:

            def _fault(i):
                if i == swap_at:
                    save_checkpoint(ckpt_dir, 1, next_params,
                                    metadata={"epoch": 2})

            results, hung = _fleet_load(
                router, [catalog[i % len(catalog)] for i in range(total)],
                6, stagger=0.02, on_index=_fault)
            deadline = time.monotonic() + CHAOS_RECOVER_S
            while time.monotonic() < deadline:
                if all(s.epoch == 2 for s in servers):
                    break
                time.sleep(0.02)
            else:
                failures.append(f"replicas never converged to epoch 2: "
                                f"{[s.epoch for s in servers]}")
            # post-swap wave REPEATS pre-swap catalog prompts: their
            # prefixes were cached under epoch-1 weights, so stale pages
            # surviving the fence would feed these prefills
            post, hung_post = _fleet_load(router, catalog[:4], 4)
    finally:
        _stop_replicas(servers)
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    totals = _totals(core.REGISTRY.snapshot())
    swaps = totals.get("serve_weight_swaps_total", 0)
    fences = totals.get("router_fence_violations_total", 0)
    hits = totals.get("serve_prefix_cache_hits_total", 0)
    done = [r for r in results
            if isinstance(r, dict) and r["reason"] == "complete"]
    epochs_seen = sorted({r["epoch"] for r in done})
    if hung or hung_post:
        failures.append("request thread(s) hung through the swap")
    if len(done) != total:
        bad = [r for r in results if r not in done]
        failures.append(f"{len(bad)} stream(s) did not complete cleanly "
                        f"through the swap: {bad[:3]!r}")
    if fences:
        failures.append(f"{fences} fence violation(s): a stream observed "
                        "two epochs")
    if swaps != 2:
        failures.append(f"weight swaps {swaps}, want exactly 1 per replica")
    if not set(epochs_seen) <= {1, 2}:
        failures.append(f"unknown epochs in streams: {epochs_seen}")
    if 1 not in epochs_seen:
        failures.append("no stream completed on the pre-swap epoch "
                        "(swap landed before traffic?)")
    if hits < 1:
        failures.append("the prefix cache never engaged — the "
                        "invalidation check proved nothing")
    bad_post = [r for r in post
                if not (isinstance(r, dict) and r["reason"] == "complete"
                        and r["epoch"] == 2)]
    if bad_post:
        failures.append(f"post-swap traffic not entirely on epoch 2: "
                        f"{bad_post[:3]!r}")
    stale = 0
    for i, r in enumerate(post):
        if not isinstance(r, dict) or r["reason"] != "complete":
            continue
        want = np.asarray(greedy_generate(
            next_params, catalog[i][None], 4))[0].tolist()
        if r["tokens"] != want:
            stale += 1
            failures.append(
                f"STALE KV past the epoch fence: post-swap stream for "
                f"catalog[{i}] decoded {r['tokens']} on epoch-2 weights, "
                f"reference says {want}")
    return {"requests": total, "completed": len(done),
            "stream_epochs": epochs_seen, "swaps": swaps,
            "fence_violations": fences, "prefix_cache_hits": hits,
            "stale_kv_streams": stale}, failures


# ---------------------------------------------------------------------------
# Traffic scenarios (docs/OBSERVABILITY.md): realistic request mixes
# driving the observability plane — windowed SLOs and the obs-driven
# autoscaler — instead of injected faults.

def _throttle_ticks(srv, delay: float):
    """Make one replica's decode step cost ``delay`` seconds: queueing
    (and therefore TTFT under load) becomes a deterministic function of
    backlog instead of machine speed."""
    orig_tick = srv.engine.tick

    def _slow_tick(*a, **kw):
        time.sleep(delay)
        return orig_tick(*a, **kw)

    srv.engine.tick = _slow_tick


_TTFT_RULE = {"name": "ttft-p95", "kind": "quantile",
              "metric": "serve_ttft_seconds", "q": 0.95,
              "target": 0.05, "window_s": 3.0}


def _scenario_zipf_mix(rounds, seed, host):
    """Zipf-popularity SHARED-PREFIX catalog over a 2-replica fleet
    with the radix prefix cache on: a few head prompts dominate, every
    prompt opens with a system prompt from a 3-entry pool, so repeats
    and siblings hit cached KV pages.  Greedy decode is a fleet-wide
    contract — every repeat of a catalog prompt must produce the
    IDENTICAL token stream whether its prefill came from compute or
    from cached pages, on whichever replica served it.  The cache must
    actually engage (hits counted, ``cached_tokens`` surfaced on the
    wire) and the steady-state TTFT p95 must hold — cache churn under
    page pressure may not degrade into re-prefill storms or stalls."""
    from distlearn_tpu.serve.router import Router
    params = _lm_params()
    port = _reserve_window(2, host)
    servers = _spawn_replicas(host, port, 2, params, prefix_cache=True)
    catalog = _prefixed_prompts(10, seed)
    weights = 1.0 / np.arange(1, 11) ** 1.5
    weights /= weights.sum()
    total = rounds * 3
    idx = np.random.default_rng(seed).choice(10, size=total, p=weights)
    try:
        # a tight health_ttl: with the cache on, requests drain fast
        # enough that a stale load snapshot would pin the whole wave to
        # the tie-winning list head
        # warm EVERY replica's compiled paths (prefill buckets,
        # cached-suffix chunks, the tick) so the asserted wave measures
        # steady state, not jit compiles — a fleet-wide router would
        # send the whole warmup to one fast replica
        for i in range(2):
            with Router([(host, port + i)], dial_deadline=1.0) as warm:
                _fleet_load(warm, catalog[:4], 4)
        with Router([(host, port + i) for i in range(2)],
                    health_ttl=0.005, dial_deadline=1.0) as router:
            snap0 = core.REGISTRY.snapshot()
            results, hung = _fleet_load(
                router, [catalog[int(k)] for k in idx], 4, stagger=0.003)
    finally:
        _stop_replicas(servers)
    snap = core.REGISTRY.snapshot()
    totals = _totals(snap)
    dispatched = _labeled(snap, "router_dispatch_total")
    done = [r for r in results
            if isinstance(r, dict) and r["reason"] == "complete"]
    failures = []
    if hung:
        failures.append(f"{hung} request thread(s) hung")
    if len(done) != total:
        bad = [r for r in results if r not in done]
        failures.append(f"only {len(done)}/{total} completed: {bad[:3]!r}")
    streams: dict[int, set] = {}
    for k, r in zip(idx, results):
        if isinstance(r, dict) and r["reason"] == "complete":
            streams.setdefault(int(k), set()).add(tuple(r["tokens"]))
    skewed = {k: len(v) for k, v in streams.items() if len(v) != 1}
    if skewed:
        failures.append("cached and uncached prefills disagreed on "
                        "repeated prompts (prompt -> distinct streams): "
                        f"{skewed}")
    if len(dispatched) < 2:
        failures.append("the mix never spread past one replica")
    counts = np.bincount(idx, minlength=10)
    if counts.max() < total / 4:
        failures.append(f"the zipf draw lost its head: {counts.tolist()}")
    completed_ctr = (
        sum(v for lbl, v in _labeled(snap, "serve_requests_total").items()
            if "complete" in str(lbl))
        - sum(v for lbl, v in _labeled(snap0, "serve_requests_total")
              .items() if "complete" in str(lbl)))
    if completed_ctr != len(done):
        failures.append(f"serve_requests_total{{complete}} = "
                        f"{completed_ctr} != {len(done)} completions")
    hits = totals.get("serve_prefix_cache_hits_total", 0)
    if hits < total // 8:
        failures.append(f"prefix cache never engaged: {hits} hits over "
                        f"{total} shared-prefix requests")
    if not any(r.get("cached_tokens") for r in done):
        failures.append("no stream reported cached_tokens despite the "
                        "shared-prefix catalog")
    # a generous absolute bound: the burst queues ~30 deep on 4 slots,
    # so p95 mostly measures queue wait (~1s here); the bound catches a
    # cache bug degenerating into admission stalls or retry storms
    # (deadlocks read as inf), not machine-speed jitter
    p95 = _hist_p95(_hist_sample(snap, "serve_ttft_seconds"),
                    _hist_sample(snap0, "serve_ttft_seconds"))
    if p95 is None or p95 > 5.0:
        failures.append(f"TTFT p95 did not hold under shared-prefix "
                        f"traffic: {p95}")
    return {"requests": total, "completed": len(done),
            "head_share": round(float(counts.max()) / total, 3),
            "distinct_prompts": int((counts > 0).sum()),
            "replicas_dispatched": len(dispatched),
            "prefix_cache_hits": hits,
            "cached_streams": sum(1 for r in done
                                  if r.get("cached_tokens")),
            "ttft_p95": p95}, failures


def _scenario_diurnal(rounds, seed, host):
    """A one-day sine of wave sizes (trough 1 -> peak 8 -> trough 1)
    against a single throttled replica, with the full telemetry loop
    watching: export endpoint -> Collector -> windowed TTFT-p95 SLO.
    The peak waves must breach (queueing behind the throttled ticks is
    deterministic), and once the trough traffic leaves the window the
    rule must recover — both transitions counted."""
    from distlearn_tpu.obs import agg as obs_agg
    from distlearn_tpu.obs.export import start_http_server
    from distlearn_tpu.serve.router import Router
    params = _lm_params()
    port = _reserve_window(1, host)
    (srv,) = _spawn_replicas(host, port, 1, params, num_slots=2)
    _throttle_ticks(srv, 0.02)
    exp = start_http_server(0, host)
    collector = obs_agg.Collector(endpoints=[(host, exp.port)])
    slo = obs_agg.SLOEngine([dict(_TTFT_RULE)])
    peak = 8
    curve = [1 + int(round((peak - 1) * 0.5 *
                           (1 - math.cos(2 * math.pi * p / rounds))))
             for p in range(rounds)]
    results: list = []
    hung_total = 0
    phase_ok: list[bool] = []
    failures: list = []
    try:
        with Router([(host, port)], health_ttl=0.05,
                    dial_deadline=1.0) as router:
            for p, lvl in enumerate(curve):
                out, hung = _fleet_load(
                    router, _serve_prompts(lvl, seed + p), 4,
                    stagger=0.005)
                results.extend(out)
                hung_total += hung
                phase_ok.append(slo.evaluate(collector.poll())[0]["ok"])
            deadline = time.monotonic() + CHAOS_SETTLE_S
            while time.monotonic() < deadline:
                if slo.evaluate(collector.poll())[0]["ok"]:
                    break
                time.sleep(0.1)
            else:
                failures.append("the windowed TTFT SLO never recovered "
                                "after the trough")
    finally:
        exp.close()
        _stop_replicas([srv])
    total = sum(curve)
    totals = _totals(core.REGISTRY.snapshot())
    done = [r for r in results
            if isinstance(r, dict) and r["reason"] == "complete"]
    if hung_total:
        failures.append(f"{hung_total} request thread(s) hung")
    if len(done) != total:
        failures.append(f"only {len(done)}/{total} completed")
    if all(phase_ok[p] for p, lvl in enumerate(curve) if lvl == peak):
        failures.append(f"no peak wave (size {peak}) breached the SLO: "
                        f"curve={curve} ok={phase_ok}")
    if totals.get("slo_breaches_total", 0) < 1:
        failures.append("slo_breaches_total never fired")
    if totals.get("slo_recoveries_total", 0) < 1:
        failures.append("slo_recoveries_total never fired")
    fleet_ttft = collector.fleet.histogram("serve_ttft_seconds")
    if not fleet_ttft or fleet_ttft["count"] != total:
        failures.append(f"fleet TTFT histogram count "
                        f"{fleet_ttft and fleet_ttft['count']} != {total}")
    return {"requests": total, "completed": len(done), "curve": curve,
            "phases_breached": sum(1 for ok in phase_ok if not ok),
            "breaches": totals.get("slo_breaches_total", 0),
            "recoveries": totals.get("slo_recoveries_total", 0)}, failures


def _scenario_flash_crowd(rounds, seed, host):
    """The autoscaler acceptance run: a 10x request burst against a
    one-replica fleet wired to the obs-driven autoscaler
    (tools/autoscaler.py).  The windowed TTFT breach must scale the
    fleet up mid-burst, the spawned replica must take real dispatches,
    every request must complete, and once the crowd passes the rule
    must recover and cooldown must retire the fleet back to one
    replica."""
    tooldir = os.path.dirname(os.path.abspath(__file__))
    if tooldir not in sys.path:
        sys.path.insert(0, tooldir)
    from autoscaler import Actuator, Autoscaler
    from distlearn_tpu.obs import agg as obs_agg
    from distlearn_tpu.obs.export import start_http_server
    from distlearn_tpu.serve.router import Router
    params = _lm_params()
    port = _reserve_window(3, host)
    tick_s = 0.05
    (base_srv,) = _spawn_replicas(host, port, 1, params, num_slots=2)
    _throttle_ticks(base_srv, tick_s)
    exp = start_http_server(0, host)
    collector = obs_agg.Collector(endpoints=[(host, exp.port)])
    rule = dict(_TTFT_RULE, target=0.1, window_s=2.5)
    slo = obs_agg.SLOEngine([rule])
    extra: list = []
    failures: list = []
    baseline = max(2, rounds // 5)
    burst = baseline * 10
    try:
        with Router([(host, port)], health_ttl=0.02,
                    dial_deadline=1.0) as router:

            def _spawn():
                p = port + 1 + len(extra)
                (srv,) = _spawn_replicas(host, p, 1, params, num_slots=2)
                _throttle_ticks(srv, tick_s)
                extra.append(srv)
                return (srv, router.add_replica(host, p))

            def _retire(handle):
                srv, name = handle
                router.remove_replica(name)
                srv.stop()

            # warm the decode path first: the first-admit jit compile
            # counts as a TTFT sample, and a compile-second sample must
            # leave the window before the scaler is armed or it would
            # scale on warmup, not on the crowd
            _fleet_load(router, _serve_prompts(2, seed + 7), 4)
            deadline = time.monotonic() + CHAOS_SETTLE_S
            while time.monotonic() < deadline:
                if slo.evaluate(collector.poll())[0]["ok"]:
                    break
                time.sleep(0.1)
            else:
                failures.append("warmup TTFT never left the SLO window")
            snap0 = _totals(core.REGISTRY.snapshot())

            scaler = Autoscaler(
                collector, slo,
                Actuator(spawn=_spawn, retire=_retire, min_size=1,
                         max_size=3, initial=1),
                scale_on={rule["name"]}, cooldown_s=1.0)

            # baseline: light load, the scaler must hold at one replica
            pre, hung_pre = _fleet_load(
                router, _serve_prompts(baseline, seed), 4, stagger=0.05)
            report = scaler.step()
            if report["action"] != "hold" or report["size"] != 1:
                failures.append(f"baseline load moved the scaler: "
                                f"{report['action']} -> {report['size']}")

            # flash crowd: 10x the baseline wave.  Two constraints pick
            # the shape: arrivals must exceed the one-replica drain rate
            # (2 slots per tick_s => ~2/(9*tick_s) req/s at 8 tokens
            # each) so the queue really builds and TTFT really breaches,
            # AND the submit window must outlive the scaler's reaction
            # (~poll interval + one breach-visible TTFT sample) so the
            # spawned replica still has arrivals left to dispatch —
            # requests route at submit time, not from a shared queue
            box: dict = {}

            def _crowd():
                box["out"] = _fleet_load(
                    router, _serve_prompts(burst, seed + 1), 8,
                    stagger=0.15)

            crowd = threading.Thread(target=_crowd, daemon=True)
            crowd.start()
            peak_size = 1
            while crowd.is_alive():
                peak_size = max(peak_size, scaler.step()["size"])
                time.sleep(0.1)
            crowd.join(CHAOS_RECOVER_S)
            results, hung = box.get("out", ([], burst))

            # aftermath: keep the loop running until the SLO recovers
            # and cooldown retires the fleet back to baseline
            deadline = time.monotonic() + CHAOS_SETTLE_S
            while time.monotonic() < deadline:
                report = scaler.step()
                if report["size"] == 1 and report["events"][0]["ok"]:
                    break
                time.sleep(0.1)
            else:
                failures.append(
                    f"fleet never cooled back down: size "
                    f"{report['size']}, ok {report['events'][0]['ok']}")
            left = router.replica_names()
    finally:
        exp.close()
        _stop_replicas([base_srv] + extra)
    snap = core.REGISTRY.snapshot()
    totals = _totals(snap)
    scale_events = _labeled(snap, "autoscaler_scale_events_total")
    ups = sum(v for lbl, v in scale_events.items() if "up" in str(lbl))
    downs = sum(v for lbl, v in scale_events.items()
                if "down" in str(lbl))
    dispatched = _labeled(snap, "router_dispatch_total")
    done = [r for r in results
            if isinstance(r, dict) and r["reason"] == "complete"]
    pre_done = [r for r in pre
                if isinstance(r, dict) and r["reason"] == "complete"]
    if hung_pre or hung:
        failures.append(f"{hung_pre + hung} request thread(s) hung")
    if len(pre_done) != baseline or len(done) != burst:
        failures.append(f"completions {len(pre_done)}+{len(done)} != "
                        f"{baseline}+{burst}")
    if peak_size < 2 or ups < 1:
        failures.append(f"the crowd never scaled the fleet up "
                        f"(peak {peak_size}, ups {ups})")
    if downs < 1:
        failures.append("cooldown never retired a replica")
    if len(left) != 1:
        failures.append(f"{len(left)} replicas left in the router, "
                        "want the baseline 1")
    if len(dispatched) < 2:
        failures.append("no dispatch ever landed on a spawned replica")
    breaches = totals.get("slo_breaches_total", 0) \
        - snap0.get("slo_breaches_total", 0)
    recoveries = totals.get("slo_recoveries_total", 0) \
        - snap0.get("slo_recoveries_total", 0)
    if breaches < 1:
        failures.append("the flash crowd never breached the SLO")
    if recoveries < 1:
        failures.append("the SLO never recovered after the crowd")
    return {"baseline": baseline, "burst": burst,
            "completed": len(pre_done) + len(done),
            "peak_size": peak_size, "scale_ups": ups,
            "scale_downs": downs, "breaches": breaches,
            "recoveries": recoveries,
            "replicas_dispatched": len(dispatched)}, failures


_SCENARIOS = {
    "flash_join": _scenario_flash_join,
    "rolling_leave": _scenario_rolling_leave,
    "slow_node": _scenario_slow_node,
    "partition_heal": _scenario_partition_heal,
    "replica_kill": _scenario_replica_kill,
    "slow_replica": _scenario_slow_replica,
    "overload_shed": _scenario_overload_shed,
    "swap_during_traffic": _scenario_swap_during_traffic,
    "zipf_mix": _scenario_zipf_mix,
    "diurnal": _scenario_diurnal,
    "flash_crowd": _scenario_flash_crowd,
}


def run_scenario(name: str, rounds: int = 12, seed: int = 0,
                 host: str = "127.0.0.1") -> dict:
    """Run one named chaos scenario (elastic membership or serving
    fleet — see module docstring) and assert its invariants + zero
    fd/thread leaks.  Deterministically seeded: every injected fault
    decision flows from ``seed`` (FaultPlan per-link RNG streams, the
    request mix of the serve scenarios)."""
    if name not in _SCENARIOS:
        raise ValueError(f"unknown scenario {name!r} "
                         f"(have: {', '.join(sorted(_SCENARIOS))})")
    if rounds < 8:
        raise ValueError("scenarios need rounds >= 8 (join/leave/fault "
                         "rounds must stay distinct)")
    core.configure(True)
    core.REGISTRY.reset()
    fd_base, th_base = _fd_count(), threading.active_count()
    try:
        fields, failures = _SCENARIOS[name](rounds, seed, host)
        fd_end, th_end = _settle_leaks(fd_base, th_base)
        if fd_end > fd_base + 2:
            failures.append(f"fd leak: {fd_base} -> {fd_end}")
        if th_end > th_base:
            failures.append(f"thread leak: {th_base} -> {th_end}")
        report = {"scenario": name, "rounds": rounds, "seed": seed,
                  **fields, "fds": [fd_base, fd_end],
                  "threads": [th_base, th_end], "failures": failures}
        if failures:
            raise AssertionError(f"chaos scenario {name} failed: "
                                 + "; ".join(failures)
                                 + f"\n{json.dumps(report, indent=2)}")
        return report
    finally:
        core.REGISTRY.reset()
        core.configure(None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="chaos", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    pp = sub.add_parser("parity", help="kill/promote bitwise-parity soak")
    pp.add_argument("--rounds", type=int, default=16)
    pp.add_argument("--kills", default="6",
                    help="comma-separated kill rounds (1..rounds-1)")
    pp.add_argument("--shards", type=int, default=4)
    pp.add_argument("--no-overlap", action="store_true")
    pp.add_argument("--mid-flight", action="store_true",
                    help="kill while the round's delta is on the wire")
    pp.add_argument("--ckpt-every", type=int, default=1)
    cp = sub.add_parser("churn", help="multi-client liveness soak")
    cp.add_argument("--rounds", type=int, default=12)
    cp.add_argument("--clients", type=int, default=3)
    cp.add_argument("--shards", type=int, default=4)
    cp.add_argument("--server-kills", type=int, default=2)
    cp.add_argument("--no-overlap", action="store_true")
    sp = sub.add_parser("scenario",
                        help="elastic membership / serving fleet chaos "
                             "scenarios")
    sp.add_argument("--name", required=True,
                    choices=sorted(_SCENARIOS))
    sp.add_argument("--rounds", type=int, default=12)
    sp.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.cmd == "scenario":
        report = run_scenario(args.name, rounds=args.rounds,
                              seed=args.seed)
    elif args.cmd == "parity":
        kills = [int(k) for k in str(args.kills).split(",") if k.strip()]
        report = run_parity(rounds=args.rounds, kills=kills,
                            shards=args.shards,
                            overlap=not args.no_overlap,
                            ckpt_every=args.ckpt_every,
                            mid_flight=args.mid_flight)
    else:
        report = run_churn(rounds=args.rounds, num_clients=args.clients,
                           shards=args.shards,
                           overlap=not args.no_overlap,
                           server_kills=args.server_kills)
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
