#!/usr/bin/env python
"""autoscaler — the obs-driven scaling loop that closes the plane.

telemetry -> fleet aggregation -> SLO evaluation -> scaling action:
every process exports its registry (``obs/export.py``), an
``obs.agg.Collector`` merges the fleet view, an ``obs.agg.SLOEngine``
judges it against declarative objectives, and this loop turns breaches
into capacity:

* **serve replicas** — spawn a new replica process/instance and
  ``Router.add_replica`` it into dispatch on breach; retire the newest
  member after a full cooldown of clean rounds.
* **elastic training clients** — the same loop shape over the
  ``Join?``/``Leave?`` verbs (``AsyncEAClient.join`` / ``.leave``,
  docs/ELASTIC.md): the spawn/retire callables join or gracefully
  leave a fleet member.

The loop itself is actuator-agnostic: :class:`Actuator` wraps a
``spawn() -> handle`` / ``retire(handle)`` pair with min/max bounds, so
the serving and training cases (and tests with fake callables) share
one policy.  Policy: scale UP immediately on any watched SLO breach
(one step per round — additive increase against a p95 objective beats
a thundering spawn), scale DOWN one member per round only after
``cooldown_s`` with every watched rule clean — flash crowds end, but
TTFT must not breach again just because the crowd's tail is still
draining.

Disabled (``enabled=False`` or the ``DISTLEARN_OBS`` kill switch), the
loop takes no action and touches nothing — a fixed fleet runs bitwise
identically with or without the plane (the acceptance criterion the
``fixed_fleet`` path of ``tests/test_obsplane.py`` pins).

Traffic scenarios that exercise this loop end-to-end (Zipf request mix,
diurnal curve, 10x flash crowd): ``tools/chaos.py scenario --name
zipf_mix|diurnal|flash_crowd``.

Usage as a library (the normal case — see the runbook in
docs/OBSERVABILITY.md):

    collector = obs.Collector(endpoints=[(h, p), ...])
    slo = obs.SLOEngine([{"name": "ttft-p95", "kind": "quantile",
                          "metric": "serve_ttft_seconds",
                          "q": 0.95, "target": 0.25}])
    act = Actuator(spawn=spawn_replica, retire=retire_replica,
                   min_size=1, max_size=6, initial=1)
    Autoscaler(collector, slo, act, cooldown_s=10.0).run(
        interval=1.0, stop=stop_event)

CLI (endpoints polled over HTTP, rules from a JSON file, actions
printed instead of actuated — a dry-run fleet monitor):

    python tools/autoscaler.py --endpoint 127.0.0.1:9100 \
        --endpoint 127.0.0.1:9101 --rules slo.json --interval 2
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from distlearn_tpu import obs
from distlearn_tpu.obs import trace


class Actuator:
    """Bounded spawn/retire surface the scaling loop drives.

    ``spawn()`` returns an opaque handle (a server object, a pid, a
    client id); ``retire(handle)`` tears that member down.  Members
    retire newest-first (LIFO) — the baseline fleet the operator started
    with is the last to go.  A spawn that raises counts as no change;
    bounds are enforced here so a mis-tuned policy cannot runaway-spawn.
    """

    def __init__(self, spawn, retire, *, min_size: int = 1,
                 max_size: int = 8, initial: int = 0):
        if min_size < 0 or max_size < max(min_size, 1):
            raise ValueError(f"bad bounds [{min_size}, {max_size}]")
        self._spawn, self._retire = spawn, retire
        self.min_size, self.max_size = int(min_size), int(max_size)
        #: members this actuator spawned (the pre-existing ``initial``
        #: ones are counted in ``size`` but never retired from here)
        self._handles: list = []
        self._initial = int(initial)

    @property
    def size(self) -> int:
        return self._initial + len(self._handles)

    def scale_up(self) -> bool:
        if self.size >= self.max_size:
            return False
        self._handles.append(self._spawn())
        return True

    def scale_down(self) -> bool:
        if not self._handles or self.size <= self.min_size:
            return False
        self._retire(self._handles.pop())
        return True


class Autoscaler:
    """One control loop over (collector, SLO engine, actuator).

    ``scale_on`` names the SLO rules whose breach triggers scaling
    (``None`` = every rule the engine evaluates).  ``cooldown_s`` is
    the clean time required before any retire — measured from the last
    breach AND the last scaling action, whichever is later, so a fresh
    member gets a full window to absorb load before being judged
    surplus."""

    def __init__(self, collector, slo, actuator: Actuator, *,
                 scale_on=None, cooldown_s: float = 10.0,
                 clock=time.monotonic, enabled: bool = True):
        self.collector, self.slo, self.actuator = collector, slo, actuator
        self.scale_on = None if scale_on is None else set(scale_on)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self.enabled = bool(enabled) and obs.enabled()
        self._last_breach: float | None = None
        self._last_action: float | None = None
        self._c_events = obs.counter(
            "autoscaler_scale_events_total",
            "scaling actions taken, by direction", labels=("direction",))
        self._g_target = obs.gauge(
            "autoscaler_target_size",
            "fleet size after the last control round")

    def step(self, now: float | None = None) -> dict:
        """One control round: poll -> evaluate -> (maybe) act.  Returns
        ``{"action": "up"|"down"|"hold"|"disabled", "size", "breached",
        "events"}`` — the record the scenario harness asserts on."""
        if not self.enabled:
            return {"action": "disabled", "size": self.actuator.size,
                    "breached": [], "events": []}
        now = self._clock() if now is None else now
        fleet = self.collector.poll()
        events = self.slo.evaluate(fleet)
        watched = [e for e in events
                   if self.scale_on is None or e["slo"] in self.scale_on]
        breached = [e["slo"] for e in watched if not e["ok"]]
        action = "hold"
        if breached:
            self._last_breach = now
            if self.actuator.scale_up():
                action = "up"
                self._last_action = now
                self._c_events.labels(direction="up").inc()
                trace.record_span("autoscaler.scale_up", 0.0,
                                  size=self.actuator.size,
                                  slo=",".join(sorted(breached)))
        elif self._cooled(now):
            if self.actuator.scale_down():
                action = "down"
                self._last_action = now
                self._c_events.labels(direction="down").inc()
                trace.record_span("autoscaler.scale_down", 0.0,
                                  size=self.actuator.size)
        self._g_target.set(self.actuator.size)
        return {"action": action, "size": self.actuator.size,
                "breached": breached, "events": events}

    def _cooled(self, now: float) -> bool:
        marks = [t for t in (self._last_breach, self._last_action)
                 if t is not None]
        if not marks:
            # never breached, never acted: nothing to cool down from,
            # but also nothing says the extra capacity is surplus —
            # only shrink once a breach/recovery cycle has happened
            return self.actuator.size > self.actuator.min_size \
                and self._last_breach is not None
        return now - max(marks) >= self.cooldown_s

    def run(self, interval: float, stop: threading.Event,
            on_round=None) -> int:
        """Drive :meth:`step` every ``interval`` seconds until ``stop``
        is set; ``on_round(report)`` observes each round.  Returns the
        number of rounds run."""
        rounds = 0
        while not stop.is_set():
            report = self.step()
            rounds += 1
            if on_round is not None:
                on_round(report)
            stop.wait(interval)
        return rounds


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--endpoint", action="append", default=[],
                   metavar="HOST:PORT",
                   help="an obs export endpoint to poll (repeatable)")
    p.add_argument("--trail", action="append", default=[],
                   help="a JSONL trail to ingest (repeatable)")
    p.add_argument("--rules", required=True,
                   help="JSON file: a list of SLO rule dicts "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--rounds", type=int, default=0,
                   help="stop after N rounds (0 = run until ^C)")
    args = p.parse_args(argv)
    with open(args.rules) as fh:
        rules = json.load(fh)
    endpoints = []
    for ep in args.endpoint:
        host, _, port = ep.rpartition(":")
        endpoints.append((host, int(port)))
    collector = obs.Collector(endpoints=endpoints, trails=args.trail)
    slo = obs.SLOEngine(rules)
    # dry run: the CLI has no spawn authority — it reports the action
    # the policy WOULD take, which is the useful fleet monitor mode
    act = Actuator(spawn=lambda: "dry-run", retire=lambda h: None,
                   min_size=0, max_size=1 << 30)
    scaler = Autoscaler(collector, slo, act)
    n = 0
    try:
        while args.rounds <= 0 or n < args.rounds:
            report = scaler.step()
            n += 1
            print(json.dumps(report))
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
