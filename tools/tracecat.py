#!/usr/bin/env python
"""tracecat — stitch multi-process obs trails into per-trace waterfalls.

With ``DISTLEARN_TRACE_PROP`` on, every process participating in one
logical operation (an AsyncEA sync, a serve request) emits span records
carrying the same ``trace`` id into its own JSONL trail
(distlearn_tpu/obs/trace.py).  This tool joins those trails back into
one tree per trace:

    python tools/tracecat.py list  client.jsonl center.jsonl ...
    python tools/tracecat.py show  *.jsonl --trace <id16>
    python tools/tracecat.py show  *.jsonl            # newest trace
    python tools/tracecat.py show  *.jsonl --format json

``list`` prints one line per trace (id, root span, span count, total
wall time, processes involved).  ``show`` renders the waterfall — spans
indented by parentage, one bar per span over the trace's wall-clock
window — plus the critical-path attribution: which leg/queue-wait
dominated the trace end-to-end, and the per-span-name share of the
root's duration.

Span records carry end timestamps (``ts`` at exit) and ``dur``; starts
are reconstructed as ``ts - dur``.  Trails from one machine share a
clock; cross-machine skew shifts bars but never breaks parentage.

Record schema: docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_spans(paths: list[str]) -> list[dict]:
    """All traced span records (``type == "span"`` with a ``trace`` id)
    from the given JSONL trails.  Untraced spans and snapshot records
    are skipped; torn tail lines of live runs are tolerated.  Each
    record gains ``_src`` (the file it came from) for per-process
    attribution when the emitter set no ``proc``."""
    out = []
    for path in paths:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("type") == "span" and rec.get("trace"):
                    rec["_src"] = path
                    out.append(rec)
    return out


def group_traces(spans: list[dict]) -> dict[str, list[dict]]:
    """trace id -> its spans, each trace's spans sorted by start time."""
    by: dict[str, list[dict]] = {}
    for rec in spans:
        by.setdefault(rec["trace"], []).append(rec)
    for recs in by.values():
        recs.sort(key=_start)
    return by


def _start(rec: dict) -> float:
    return float(rec["ts"]) - float(rec["dur"])


def _proc(rec: dict) -> str:
    return rec.get("proc") or rec.get("_src", "?")


def build_tree(recs: list[dict]) -> tuple[list[dict], dict[str, list]]:
    """``(roots, children)`` of one trace: spans with no ``parent`` (or
    a parent missing from the trails — a truncated ring) are roots;
    ``children`` maps span id -> child records sorted by start."""
    by_id = {r["span"]: r for r in recs if r.get("span")}
    children: dict[str, list] = {}
    roots = []
    for r in recs:
        p = r.get("parent")
        if p and p in by_id:
            children.setdefault(p, []).append(r)
        else:
            roots.append(r)
    for v in children.values():
        v.sort(key=_start)
    roots.sort(key=_start)
    return roots, children


def critical_path(recs: list[dict]) -> list[dict]:
    """Root-to-leaf chain that determined the trace's end time: from
    each span, follow the child that FINISHED last — the leg everything
    else waited for.  (Fan-out legs run concurrently; the last to end
    gates the parent, so this is the chain to shorten first.)"""
    roots, children = build_tree(recs)
    if not roots:
        return []
    node = max(roots, key=lambda r: float(r["ts"]))
    path = [node]
    while children.get(node.get("span")):
        node = max(children[node["span"]], key=lambda r: float(r["ts"]))
        path.append(node)
    return path


def attribution(recs: list[dict]) -> list[dict]:
    """Per span-name totals for one trace: count, summed duration, and
    share of the trace's wall window — the "which leg dominated" table.
    Shares can exceed 1.0 summed: concurrent legs overlap."""
    t0 = min(_start(r) for r in recs)
    t1 = max(float(r["ts"]) for r in recs)
    wall = max(t1 - t0, 1e-12)
    by: dict[str, dict] = {}
    for r in recs:
        row = by.setdefault(r["name"], {"name": r["name"], "count": 0,
                                        "total": 0.0})
        row["count"] += 1
        row["total"] += float(r["dur"])
    for row in by.values():
        row["share"] = row["total"] / wall
    return sorted(by.values(), key=lambda r: -r["total"])


def trace_summary(tid: str, recs: list[dict]) -> dict:
    t0 = min(_start(r) for r in recs)
    t1 = max(float(r["ts"]) for r in recs)
    roots, _ = build_tree(recs)
    return {"trace": tid, "spans": len(recs),
            "root": roots[0]["name"] if roots else "?",
            "start": t0, "wall": t1 - t0,
            "procs": sorted({_proc(r) for r in recs})}


_BAR_W = 40


def waterfall(recs: list[dict]) -> list[str]:
    """Text waterfall for one trace: depth-first in start order, one
    ``[###]`` bar per span positioned on the trace's wall window."""
    t0 = min(_start(r) for r in recs)
    t1 = max(float(r["ts"]) for r in recs)
    wall = max(t1 - t0, 1e-12)
    roots, children = build_tree(recs)
    width = max((len(r["name"]) + 2 * _depth_of(r, recs)
                 for r in recs), default=10)
    lines = []

    def emit(rec, depth):
        lo = int(round((_start(rec) - t0) / wall * _BAR_W))
        hi = int(round((float(rec["ts"]) - t0) / wall * _BAR_W))
        hi = max(hi, lo + 1)
        bar = " " * lo + "#" * (hi - lo) + " " * (_BAR_W - hi)
        label = "  " * depth + rec["name"]
        lines.append(f"  {label:<{width}} |{bar}| "
                     f"{float(rec['dur']) * 1e3:9.3f} ms  {_proc(rec)}")
        for ch in children.get(rec.get("span", ""), []):
            emit(ch, depth + 1)

    for r in roots:
        emit(r, 0)
    return lines


def _depth_of(rec: dict, recs: list[dict]) -> int:
    by_id = {r["span"]: r for r in recs if r.get("span")}
    d, p = 0, rec.get("parent")
    while p and p in by_id and d < 64:
        d += 1
        p = by_id[p].get("parent")
    return d


def render_trace(tid: str, recs: list[dict]) -> str:
    s = trace_summary(tid, recs)
    out = [f"trace {tid} — {s['spans']} spans, "
           f"{s['wall'] * 1e3:.3f} ms wall, procs: {', '.join(s['procs'])}",
           ""]
    out += waterfall(recs)
    cp = critical_path(recs)
    out += ["", "  critical path (the chain the trace waited on):"]
    out += [f"    {r['name']}  {float(r['dur']) * 1e3:9.3f} ms  "
            f"[{_proc(r)}]" for r in cp]
    out += ["", f"  {'span name':<28} {'count':>5} {'total ms':>10} "
                f"{'share':>7}"]
    out += [f"  {row['name']:<28} {row['count']:>5} "
            f"{row['total'] * 1e3:>10.3f} {row['share']:>6.1%}"
            for row in attribution(recs)]
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)
    pl = sub.add_parser("list", help="one line per trace across trails")
    pl.add_argument("paths", nargs="+")
    pl.add_argument("--format", choices=("text", "json"), default="text")
    ps = sub.add_parser("show", help="waterfall + critical path of one "
                                     "trace")
    ps.add_argument("paths", nargs="+")
    ps.add_argument("--trace", help="trace id (default: newest trace)")
    ps.add_argument("--format", choices=("text", "json"), default="text")
    args = p.parse_args(argv)

    traces = group_traces(load_spans(args.paths))
    if not traces:
        print("no traced spans found (is DISTLEARN_TRACE_PROP on?)",
              file=sys.stderr)
        return 1
    if args.cmd == "list":
        rows = sorted((trace_summary(t, rs) for t, rs in traces.items()),
                      key=lambda s: s["start"])
        if args.format == "json":
            print(json.dumps(rows, indent=2))
        else:
            for s in rows:
                print(f"{s['trace']}  {s['root']:<20} {s['spans']:>4} "
                      f"spans  {s['wall'] * 1e3:9.3f} ms  "
                      f"{len(s['procs'])} procs")
        return 0
    tid = args.trace
    if tid is None:
        tid = max(traces, key=lambda t: trace_summary(t, traces[t])["start"])
    if tid not in traces:
        print(f"trace {tid!r} not found in these trails", file=sys.stderr)
        return 1
    if args.format == "json":
        cp = critical_path(traces[tid])
        print(json.dumps({"summary": trace_summary(tid, traces[tid]),
                          "spans": traces[tid],
                          "critical_path": [r["span"] for r in cp],
                          "attribution": attribution(traces[tid])},
                         indent=2))
    else:
        print(render_trace(tid, traces[tid]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
