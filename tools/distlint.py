#!/usr/bin/env python
"""distlint CLI: lint the repo's step functions and comm protocols.

    python tools/distlint.py --all              # every registered family
    python tools/distlint.py --family lm        # one family
    python tools/distlint.py --family sgd --family ea
    python tools/distlint.py --list             # what's registered
    python tools/distlint.py --all --disable DL004

Exit code 0 when no error-severity findings survive suppression, 1 when
findings remain, 2 on usage errors.  Rule catalog: docs/LINT.md.
"""

import argparse
import os
import sys

# The step families need a multi-device mesh; force 8 virtual CPU devices
# BEFORE jax initialises (tier-1 runs the same way via tests/conftest.py).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from distlearn_tpu.utils import compat  # noqa: E402

compat.install()

from distlearn_tpu.lint.core import RULES, format_findings  # noqa: E402
from distlearn_tpu.lint import registry  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="distlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--all", action="store_true",
                    help="lint every registered family")
    ap.add_argument("--family", action="append", default=[],
                    metavar="NAME", help="lint one family (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="list registered families and rules, then exit")
    ap.add_argument("--disable", action="append", default=[],
                    metavar="RULE", help="suppress a rule id (repeatable)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print findings only, no per-unit OK lines")
    args = ap.parse_args(argv)

    fams = registry.families()
    if args.list:
        print("families:")
        for name, e in fams.items():
            print(f"  {name:10s} {e.description}")
        print("rules:")
        for rid, (title, sev) in RULES.items():
            print(f"  {rid}  [{sev}] {title}")
        return 0

    wanted = list(fams) if args.all else args.family
    if not wanted:
        ap.print_usage(sys.stderr)
        print("distlint: pass --all, --family NAME, or --list",
              file=sys.stderr)
        return 2
    unknown = [f for f in wanted if f not in fams]
    if unknown:
        print(f"distlint: unknown family {unknown} "
              f"(have: {', '.join(fams)})", file=sys.stderr)
        return 2
    try:
        suppress = set(args.disable)
        results = []
        for fam in wanted:
            results += registry.run_family(fam, suppress=suppress)
    except ValueError as e:   # unknown rule id in --disable
        print(f"distlint: {e}", file=sys.stderr)
        return 2

    bad = 0
    for res in results:
        if res.findings:
            print(format_findings(res.findings, header=f"{res.name}:"))
        elif not args.quiet:
            print(f"{res.name}: OK")
        bad += 0 if res.ok else 1
    total = sum(len(r.findings) for r in results)
    print(f"distlint: {len(results)} unit(s), {total} finding(s)"
          + (f", {bad} with errors" if bad else ""))
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
