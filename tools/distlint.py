#!/usr/bin/env python
"""distlint CLI: lint the repo's step functions and comm protocols.

    python tools/distlint.py --all              # every registered family
    python tools/distlint.py --family lm        # one family
    python tools/distlint.py --family sgd --family ea
    python tools/distlint.py --list             # what's registered
    python tools/distlint.py --all --disable DL004
    python tools/distlint.py --all --format json
    python tools/distlint.py --model            # protocol model checking
    python tools/distlint.py --races            # lockset race detection
    python tools/distlint.py --update-budgets   # re-baseline cost lockfiles

Exit code 0 when no error-severity findings survive suppression, 1 when
findings remain, 2 on usage errors.  Rule catalog: docs/LINT.md.

``--update-budgets`` compiles every selected family, rewrites its budget
lockfile (``distlearn_tpu/lint/budgets/<family>.json``) from the fresh
numbers, and exits 0 — commit the diff alongside the change that moved
the traffic.  ``--format json`` emits machine-readable findings plus the
per-family cost tables (bytes per collective kind per mesh axis, op
counts, peak memory).
"""

import argparse
import json
import os
import sys

# The step families need a multi-device mesh; force 8 virtual CPU devices
# BEFORE jax initialises (tier-1 runs the same way via tests/conftest.py).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Match tests/conftest.py: the budget lockfiles carry byte counts, and x64
# widens integer temporaries — the CLI and the tier-1 gate must compile the
# exact same programs or the two contexts would disagree on the budgets.
jax.config.update("jax_enable_x64", True)

from distlearn_tpu.utils import compat  # noqa: E402

compat.install()

from distlearn_tpu.lint.core import RULES, format_findings  # noqa: E402
from distlearn_tpu.lint import budget as budget_mod  # noqa: E402
from distlearn_tpu.lint import registry  # noqa: E402


def _cost_table(reports) -> dict:
    return {name: rep.to_json() for name, rep in sorted(reports.items())}


def _print_cost_table(family: str, reports) -> None:
    for name, rep in sorted(reports.items()):
        ops = rep.ops_by_axis
        parts = [f"{k}: {v}B/{ops[k]}op"
                 for k, v in sorted(rep.bytes_by_axis.items())]
        peak = rep.peak_bytes
        parts.append(f"peak: {peak}B" if peak is not None else "peak: n/a")
        if rep.relayout_ops is not None:
            parts.append(f"entry relayouts: {rep.relayout_ops}")
        print(f"  {family}:{name:24s} " + ("; ".join(parts) or "no traffic"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="distlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--all", action="store_true",
                    help="lint every registered family")
    ap.add_argument("--family", action="append", default=[],
                    metavar="NAME", help="lint one family (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="list registered families and rules, then exit")
    ap.add_argument("--model", action="store_true",
                    help="run the explicit-state protocol models + "
                         "schedule conformance (shorthand for "
                         "--family model)")
    ap.add_argument("--races", action="store_true",
                    help="run the static lockset race detector "
                         "(shorthand for --family races)")
    ap.add_argument("--disable", action="append", default=[],
                    metavar="RULE", help="suppress a rule id (repeatable)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="output format (json: findings + cost tables)")
    ap.add_argument("--update-budgets", action="store_true",
                    help="rewrite the selected families' cost budget "
                         "lockfiles from a fresh compile (then commit them)")
    ap.add_argument("--budget-dir", default=None, metavar="DIR",
                    help="override the lockfile directory "
                         "(default: distlearn_tpu/lint/budgets)")
    ap.add_argument("--costs", action="store_true",
                    help="print the per-unit cost tables with text output")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print findings only, no per-unit OK lines")
    args = ap.parse_args(argv)

    fams = registry.families()
    if args.list:
        print("families:")
        for name, e in fams.items():
            print(f"  {name:10s} {e.description}")
        print("rules:")
        for rid, (title, sev) in RULES.items():
            print(f"  {rid}  [{sev}] {title}")
        return 0

    if args.model:
        args.family.append("model")
    if args.races:
        args.family.append("races")
    wanted = list(fams) if (args.all or (args.update_budgets
                                         and not args.family)) \
        else args.family
    if not wanted:
        ap.print_usage(sys.stderr)
        print("distlint: pass --all, --family NAME, or --list",
              file=sys.stderr)
        return 2
    unknown = [f for f in wanted if f not in fams]
    if unknown:
        print(f"distlint: unknown family {unknown} "
              f"(have: {', '.join(fams)})", file=sys.stderr)
        return 2

    if args.update_budgets:
        for fam in wanted:
            _, reports = registry.run_family_costed(
                fam, budget_dir=args.budget_dir)
            path = budget_mod.save_budget(fam, reports,
                                          budget_dir=args.budget_dir)
            print(f"distlint: wrote {path} ({len(reports)} unit(s))")
        return 0

    try:
        suppress = set(args.disable)
        results = []
        all_reports = {}
        for fam in wanted:
            res, reports = registry.run_family_costed(
                fam, suppress=suppress, budget_dir=args.budget_dir)
            results += res
            all_reports[fam] = reports
    except ValueError as e:   # unknown rule id in --disable
        print(f"distlint: {e}", file=sys.stderr)
        return 2

    bad = sum(0 if r.ok else 1 for r in results)
    total = sum(len(r.findings) for r in results)

    if args.format == "json":
        doc = {
            "findings": [
                {"unit": r.name, "rule": f.rule, "severity": f.severity,
                 "where": f.where, "message": f.message}
                for r in results for f in r.findings],
            "costs": {fam: _cost_table(reports)
                      for fam, reports in all_reports.items()},
            "compiles": {r.name.split(":", 1)[0]: r.info
                         for r in results
                         if r.name.endswith(":compiles") and r.info},
            "rules": sorted(RULES),
            "info": {r.name: r.info for r in results if r.info},
            "units": len(results),
            "errors": bad,
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 1 if bad else 0

    for res in results:
        if res.findings:
            print(format_findings(res.findings, header=f"{res.name}:"))
        elif not args.quiet:
            extra = ""
            if "states" in res.info:
                extra = f" ({res.info['states']:,} states)"
            elif "count" in res.info:
                extra = (f" ({res.info['count']} compile(s), "
                         f"~{res.info['warmup_s_estimate']}s warmup)")
            print(f"{res.name}: OK{extra}")
    if args.costs:
        print("costs (bytes/step per device, post-fusion):")
        for fam, reports in all_reports.items():
            _print_cost_table(fam, reports)
    print(f"distlint: {len(results)} unit(s), {total} finding(s)"
          + (f", {bad} with errors" if bad else ""))
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
