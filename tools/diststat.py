#!/usr/bin/env python
"""diststat — aggregate a distlearn obs JSONL run into latency tables,
or diff two runs.

The obs subsystem (distlearn_tpu/obs/) spills span records and registry
snapshots to JSONL; this tool turns that trail into the numbers
docs/PERF.md used to recompute by hand:

    python tools/diststat.py summarize run.jsonl [more.jsonl ...]
    python tools/diststat.py summarize run.jsonl --format json
    python tools/diststat.py diff before.jsonl after.jsonl
    python tools/diststat.py merge center.jsonl client-*.jsonl

``summarize`` reports per-span-name count/p50/p95/p99/total (exact —
computed from the individual span durations, not histogram buckets),
final counter values (per label set and summed per name), gauges, and
histogram summaries.  Multiple files merge: spans concatenate, counters
sum across files (one file per process is the normal layout — server
and each client spill separately).  Serving runs additionally get the
derived serving/router tables and the raw-speed table (radix
prefix-cache hit rate and retained pages, speculative-decode accepted
tokens per tick, chunked-prefill dispatch mix — docs/SERVING.md).
``diff`` subtracts run A's counter totals and span quantiles from
run B's.

``merge`` is the FLEET view (one trail per process): counters and span
quantiles fleet-wide with a per-process breakdown column, histogram
merges through ``obs.agg`` (the same math the live Collector runs),
the SLO table (rule state, breach/recovery counts), the autoscaler
table (target size, scale events by direction), per-process obs health
(``obs_spans_dropped_total`` — nonzero means the 4096-entry span ring
wrapped and this report undercounts), and the chronological fleet
event log (``slo.breach`` / ``slo.recover`` / ``autoscaler.scale_*``).

Record schema: docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _percentile(xs: list[float], q: float) -> float:
    """Nearest-rank percentile on a sorted copy (small-n friendly)."""
    if not xs:
        return float("nan")
    xs = sorted(xs)
    k = max(0, min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[k]


def _label_key(labels: dict) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{v}"'
                          for k, v in sorted(labels.items())) + "}"


def load_run(paths: list[str]) -> dict:
    """Parse one run (1+ JSONL files) into ``{"spans": {...},
    "counters": {...}, "counter_totals": {...}, "gauges": {...},
    "histograms": {...}, "records": n}``."""
    spans: dict[str, list[float]] = {}
    span_errs: dict[str, int] = {}
    counters: dict[str, float] = {}
    counter_totals: dict[str, float] = {}
    gauges: dict[str, float] = {}
    hists: dict[str, dict] = {}
    nrec = 0
    for path in paths:
        last_snap = None
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue          # torn tail line of a live run
                nrec += 1
                if rec.get("type") == "span":
                    spans.setdefault(rec["name"], []).append(
                        float(rec["dur"]))
                    if rec.get("err"):
                        span_errs[rec["name"]] = \
                            span_errs.get(rec["name"], 0) + 1
                elif rec.get("type") == "snapshot":
                    last_snap = rec
        if last_snap is None:
            continue
        for fam in last_snap.get("metrics", []):
            name, kind = fam["name"], fam["kind"]
            for s in fam.get("samples", []):
                key = name + _label_key(s.get("labels", {}))
                if kind == "counter":
                    counters[key] = counters.get(key, 0) + s["value"]
                    counter_totals[name] = \
                        counter_totals.get(name, 0) + s["value"]
                elif kind == "gauge":
                    gauges[key] = s["value"]
                elif kind == "histogram":
                    h = hists.setdefault(key, {"sum": 0.0, "count": 0})
                    h["sum"] += s["sum"]
                    h["count"] += s["count"]
    return {"records": nrec, "spans": spans, "span_errs": span_errs,
            "counters": counters, "counter_totals": counter_totals,
            "gauges": gauges, "histograms": hists}


_WIRE_FAMS = {"wire_packed_frames_total": "frames",
              "wire_packed_bytes_total": "wire_bytes",
              "wire_logical_bytes_total": "logical_bytes"}


def wire_table(counters: dict) -> dict:
    """Derive the packed-wire table from the wire_* counter families:
    per codec, frame count, wire bytes, logical (pre-encoding) bytes, and
    the compression ratio logical/wire.  Empty when the run never sent a
    packed frame."""
    tab: dict[str, dict] = {}
    for key, v in counters.items():
        for fam, col in _WIRE_FAMS.items():
            prefix = fam + '{codec="'
            if key.startswith(prefix) and key.endswith('"}'):
                codec = key[len(prefix):-2]
                row = tab.setdefault(codec, {c: 0.0 for c in
                                             _WIRE_FAMS.values()})
                row[col] += v
    for row in tab.values():
        row["ratio"] = (row["logical_bytes"] / row["wire_bytes"]
                        if row["wire_bytes"] else float("nan"))
    return dict(sorted(tab.items()))


_SHARD_SYNCS = "async_ea_shard_syncs_total"
_SHARD_BYTES = "async_ea_shard_wire_bytes_total"
_SHARD_APPLY = "async_ea_shard_apply_seconds"


def _shard_label(key: str, fam: str) -> str | None:
    prefix = fam + '{shard="'
    if key.startswith(prefix) and key.endswith('"}'):
        return key[len(prefix):-2]
    return None


def shard_table(counters: dict, histograms: dict) -> dict:
    """Derive the sharded parameter-server balance table from the
    async_ea_shard_* families: per shard, stripe legs served, wire bytes
    moved (center down + delta up) and the per-stripe apply latency.
    Empty when the run never served a sharded sync — the whole table is
    the load-balance check for wire.plan_stripes (byte counts should be
    near-equal across rows; leg counts exactly equal unless a client
    died mid-sync)."""
    tab: dict[str, dict] = {}

    def row(shard):
        return tab.setdefault(shard, {
            "legs": 0.0, "wire_bytes": 0.0, "applies": 0,
            "apply_mean": float("nan")})

    for key, v in counters.items():
        s = _shard_label(key, _SHARD_SYNCS)
        if s is not None:
            row(s)["legs"] += v
        s = _shard_label(key, _SHARD_BYTES)
        if s is not None:
            row(s)["wire_bytes"] += v
    for key, h in histograms.items():
        s = _shard_label(key, _SHARD_APPLY)
        if s is not None:
            r = row(s)
            r["applies"] += h["count"]
            r["apply_mean"] = (h["sum"] / h["count"] if h["count"]
                               else float("nan"))
    return dict(sorted(tab.items(), key=lambda kv: (len(kv[0]), kv[0])))


_ENCODE_HIST = "wire_encode_seconds"
_APPLY_HIST = "center_apply_seconds"
_ZC_FAM = "wire_zero_copy_total"


def codec_table(counters: dict, histograms: dict) -> dict:
    """Derive the fused wire-codec table: per stripe ('all' = whole-tree),
    the client-side encode (quantize + error-feedback) and server-side
    apply (dequantize + elastic add) histograms, plus the zero-copy
    staging hit ratio from ``wire_zero_copy_total`` (hit = one contiguous
    frame-buffer iovec per send, miss = per-leaf gather; only client
    delta-up sends stage, so a healthy EASGD fleet sits near 0.5 —
    docs/OBSERVABILITY.md).  Empty when the run never took the fused
    path — so the table doubles as the is-the-fast-path-actually-on
    check for production runs."""
    stripes: dict[str, dict] = {}

    def row(shard):
        return stripes.setdefault(shard, {
            "encodes": 0, "encode_mean": float("nan"),
            "applies": 0, "apply_mean": float("nan")})

    for key, h in histograms.items():
        s = _shard_label(key, _ENCODE_HIST)
        if s is not None and h["count"]:
            r = row(s)
            r["encodes"] += h["count"]
            r["encode_mean"] = h["sum"] / h["count"]
        s = _shard_label(key, _APPLY_HIST)
        if s is not None and h["count"]:
            r = row(s)
            r["applies"] += h["count"]
            r["apply_mean"] = h["sum"] / h["count"]
    out: dict = {}
    if stripes:
        out["stripes"] = dict(sorted(stripes.items(),
                                     key=lambda kv: (len(kv[0]), kv[0])))
    hit = counters.get(_ZC_FAM + '{result="hit"}', 0.0)
    miss = counters.get(_ZC_FAM + '{result="miss"}', 0.0)
    if hit or miss:
        out["zero_copy"] = {"hit": hit, "miss": miss,
                            "hit_ratio": hit / (hit + miss)}
    return out


_FAILOVER_COUNTERS = {
    "async_ea_evictions_total": "evictions",
    "async_ea_rejoins_total": "rejoins",
    "async_ea_failover_redials_total": "redials",
    "async_ea_failover_promotions_total": "promotions",
    "async_ea_failover_stale_refusals_total": "stale_refusals",
    "center_ckpt_saves_total": "ckpt_saves",
    "center_ckpt_restores_total": "ckpt_restores",
}
_REPLAYS_FAM = "async_ea_failover_replays_total"
_FAILOVER_SPANS = ("async_ea.promote", "async_ea.failover")


def failover_table(counter_totals: dict, counters: dict,
                   spans: dict) -> dict:
    """Derive the HA/failover table (docs/HA.md): eviction/rejoin/re-dial
    counts, promotions and checkpoint traffic, replay outcomes, and the
    promotion + client-failover latency quantiles from their spans.
    Empty when the run had no failover activity at all."""
    tab: dict = {}
    for fam, col in _FAILOVER_COUNTERS.items():
        v = counter_totals.get(fam, 0)
        if v:
            tab[col] = v
    replays = {}
    prefix = _REPLAYS_FAM + '{outcome="'
    for key, v in counters.items():
        if key.startswith(prefix) and key.endswith('"}'):
            replays[key[len(prefix):-2]] = v
    if replays:
        tab["replays"] = dict(sorted(replays.items()))
    lat = {}
    for name in _FAILOVER_SPANS:
        durs = spans.get(name)
        if durs:
            lat[name] = {"count": len(durs),
                         "p50": _percentile(durs, 50),
                         "p99": _percentile(durs, 99)}
    if lat:
        tab["latency"] = lat
    return tab


_MEMBER_COUNTERS = {
    "async_ea_membership_joins_total": "joins",
    "async_ea_membership_join_failures_total": "join_failures",
}
_LEAVES_FAM = "async_ea_membership_leaves_total"
_TAU_GAUGE = "async_ea_adaptive_tau"
_MEMBER_SPANS = ("async_ea.join", "async_ea.leave")


def membership_table(counter_totals: dict, counters: dict, gauges: dict,
                     spans: dict) -> dict:
    """Derive the elastic-membership table (docs/ELASTIC.md): Join?
    admissions and refusals, Leave? departures by pending-delta outcome
    (``flushed`` / ``clean`` / ``dropped``), the final live fleet size,
    each client's straggler-adapted effective τ, and the join/leave
    handshake latency quantiles.  Empty when the run's fleet was fixed —
    so a populated table is itself the proof the server ran elastic."""
    tab: dict = {}
    for fam, col in _MEMBER_COUNTERS.items():
        v = counter_totals.get(fam, 0)
        if v:
            tab[col] = v
    leaves = {}
    prefix = _LEAVES_FAM + '{outcome="'
    for key, v in counters.items():
        if key.startswith(prefix) and key.endswith('"}'):
            leaves[key[len(prefix):-2]] = v
    if leaves:
        tab["leaves"] = dict(sorted(leaves.items()))
    size = gauges.get("async_ea_membership_size")
    if size is not None and (tab or size):
        tab["fleet_size"] = size
    tau, tprefix = {}, _TAU_GAUGE + '{cid="'
    for key, v in gauges.items():
        if key.startswith(tprefix) and key.endswith('"}'):
            tau[key[len(tprefix):-2]] = v
    if tau:
        tab["adaptive_tau"] = dict(sorted(tau.items(),
                                          key=lambda kv: (len(kv[0]), kv[0])))
    lat = {}
    for name in _MEMBER_SPANS:
        durs = spans.get(name)
        if durs:
            lat[name] = {"count": len(durs),
                         "p50": _percentile(durs, 50),
                         "p99": _percentile(durs, 99)}
    if lat:
        tab["latency"] = lat
    return tab


_SERVE_SPANS = {"serve.ttft": "ttft", "serve.tpot": "tpot",
                "serve.prefill": "prefill", "serve.tick": "tick"}
_SERVE_OUTCOMES = 'serve_requests_total{outcome="'


def serving_table(counter_totals: dict, counters: dict, spans: dict) -> dict:
    """Derive the serving table (docs/SERVING.md): request counts by
    terminal outcome, tokens streamed, and TTFT / per-token (TPOT) /
    prefill / tick latency quantiles from the span trail — exact values
    from individual spans, not histogram buckets.  Empty when the run
    served nothing."""
    tab: dict = {}
    outcomes = {}
    for key, v in counters.items():
        if key.startswith(_SERVE_OUTCOMES) and key.endswith('"}'):
            outcomes[key[len(_SERVE_OUTCOMES):-2]] = v
    if outcomes:
        tab["requests"] = dict(sorted(outcomes.items()))
    toks = counter_totals.get("serve_tokens_total", 0)
    if toks:
        tab["tokens"] = toks
    lat = {}
    for name, col in _SERVE_SPANS.items():
        durs = spans.get(name)
        if durs:
            lat[col] = {"count": len(durs),
                        "p50": _percentile(durs, 50),
                        "p95": _percentile(durs, 95),
                        "p99": _percentile(durs, 99)}
    if lat:
        tab["latency"] = lat
    return tab


_ROUTER_TOTALS = {
    "router_retries_total": "retries",
    "router_hedges_total": "hedges",
    "router_shed_total": "sheds",
    "router_fence_violations_total": "fence_violations",
}
_ROUTER_DISPATCH = 'router_dispatch_total{replica="'
_ROUTER_SPANS = {"router.failover": "failover", "router.hedge": "hedge"}


def router_table(counter_totals: dict, counters: dict, spans: dict) -> dict:
    """Derive the fleet-router table (docs/SERVING.md): per-replica
    dispatch counts, death resubmissions, hedges, sheds and epoch-fence
    violations, plus the failover/hedge recovery latency quantiles
    (replica death or hedge fire to first token on the survivor).
    Empty when the run had no router in front of it."""
    tab: dict = {}
    dispatch = {}
    for key, v in counters.items():
        if key.startswith(_ROUTER_DISPATCH) and key.endswith('"}'):
            dispatch[key[len(_ROUTER_DISPATCH):-2]] = v
    if dispatch:
        tab["dispatch"] = dict(sorted(dispatch.items()))
    for fam, col in _ROUTER_TOTALS.items():
        v = counter_totals.get(fam, 0)
        if v:
            tab[col] = v
    lat = {}
    for name, col in _ROUTER_SPANS.items():
        durs = spans.get(name)
        if durs:
            lat[col] = {"count": len(durs),
                        "p50": _percentile(durs, 50),
                        "p99": _percentile(durs, 99)}
    if lat:
        tab["latency"] = lat
    return tab


_PREFIX_CACHE_FAMS = {
    "serve_prefix_cache_hits_total": "hits",
    "serve_prefix_cache_misses_total": "misses",
    "serve_prefix_cache_evictions_total": "evictions",
}
_SPEC_SPANS = {"serve.prefill_chunk": "prefill_chunk",
               "serve.verify": "verify"}


def raw_speed_table(counter_totals: dict, gauges: dict,
                    histograms: dict, spans: dict) -> dict:
    """Derive the serving raw-speed table (docs/SERVING.md): radix
    prefix-cache hit/miss/eviction counts with the hit rate and pages
    still retained, the speculative-decode acceptance rate (mean tokens
    emitted per slot per verify tick — 1.0 is plain-tick throughput,
    anything above is the speculation win), and the chunked-prefill /
    verify dispatch latencies.  Empty when neither the cache nor the
    drafter ever ran."""
    tab: dict = {}
    cache = {col: counter_totals[fam]
             for fam, col in _PREFIX_CACHE_FAMS.items()
             if counter_totals.get(fam)}
    if cache:
        looked = cache.get("hits", 0) + cache.get("misses", 0)
        if looked:
            cache["hit_rate"] = cache.get("hits", 0) / looked
        pages = gauges.get("serve_prefix_cache_pages")
        if pages is not None:
            cache["pages_retained"] = pages
        tab["prefix_cache"] = cache
    acc = histograms.get("serve_spec_accepted_tokens")
    if acc and acc["count"]:
        tab["speculation"] = {
            "verify_slot_ticks": acc["count"],
            "tokens_emitted": acc["sum"],
            "accepted_tokens_per_tick": acc["sum"] / acc["count"],
            "verify_dispatches": counter_totals.get(
                "serve_engine_verifies_total", 0),
        }
    chunks = counter_totals.get("serve_engine_prefill_chunks_total", 0)
    if chunks:
        tab["prefill_chunks"] = chunks
    lat = {}
    for name, col in _SPEC_SPANS.items():
        durs = spans.get(name)
        if durs:
            lat[col] = {"count": len(durs),
                        "p50": _percentile(durs, 50),
                        "p99": _percentile(durs, 99)}
    if lat:
        tab["latency"] = lat
    return tab


_SYNC_FAMS = {"sync_rounds_total": "rounds",
              "sync_host_leg_bytes_total": "host_leg_bytes",
              "sync_logical_bytes_total": "logical_bytes"}
_SYNC_SECONDS = "sync_seconds"


def _backend_label(key: str, fam: str) -> str | None:
    prefix = fam + '{backend="'
    if key.startswith(prefix) and key.endswith('"}'):
        return key[len(prefix):-2]
    return None


def sync_table(counters: dict, histograms: dict) -> dict:
    """Derive the per-backend collective-sync table from the sync_*
    families emitted by :mod:`distlearn_tpu.comm.backend`: rounds run,
    host-leg (TCP) bytes vs logical (reduced-value) bytes — their ratio
    is the hierarchical win; for HybridBackend host_leg/round should be
    ~1/L of HostBackend's at L local devices — and the mean round wall
    time with the implied syncs/s.  Empty when no backend ever synced."""
    tab: dict[str, dict] = {}

    def row(backend):
        return tab.setdefault(backend, {
            "rounds": 0.0, "host_leg_bytes": 0.0, "logical_bytes": 0.0})

    for key, v in counters.items():
        for fam, col in _SYNC_FAMS.items():
            b = _backend_label(key, fam)
            if b is not None:
                row(b)[col] += v
    for key, h in histograms.items():
        b = _backend_label(key, _SYNC_SECONDS)
        if b is not None and h["count"]:
            r = row(b)
            r["sync_mean"] = h["sum"] / h["count"]
            r["syncs_per_s"] = (h["count"] / h["sum"] if h["sum"]
                                else float("inf"))
    for r in tab.values():
        r["host_bytes_per_round"] = (r["host_leg_bytes"] / r["rounds"]
                                     if r["rounds"] else float("nan"))
        r["host_reduction"] = (r["logical_bytes"] / r["host_leg_bytes"]
                               if r["host_leg_bytes"] else float("inf"))
    return dict(sorted(tab.items()))


def summarize_run(paths: list[str]) -> dict:
    run = load_run(paths)
    span_tab = {}
    for name, durs in sorted(run["spans"].items()):
        span_tab[name] = {
            "count": len(durs),
            "errors": run["span_errs"].get(name, 0),
            "p50": _percentile(durs, 50),
            "p95": _percentile(durs, 95),
            "p99": _percentile(durs, 99),
            "total": sum(durs),
        }
    hist_tab = {}
    for key, h in sorted(run["histograms"].items()):
        mean = h["sum"] / h["count"] if h["count"] else float("nan")
        hist_tab[key] = {"count": h["count"], "sum": h["sum"], "mean": mean}
    return {"records": run["records"], "spans": span_tab,
            "counters": dict(sorted(run["counters"].items())),
            "counter_totals": dict(sorted(run["counter_totals"].items())),
            "gauges": dict(sorted(run["gauges"].items())),
            "histograms": hist_tab,
            "wire": wire_table(run["counters"]),
            "codec": codec_table(run["counters"], run["histograms"]),
            "shards": shard_table(run["counters"], run["histograms"]),
            "failover": failover_table(run["counter_totals"],
                                       run["counters"], run["spans"]),
            "membership": membership_table(run["counter_totals"],
                                           run["counters"], run["gauges"],
                                           run["spans"]),
            "serving": serving_table(run["counter_totals"],
                                     run["counters"], run["spans"]),
            "router": router_table(run["counter_totals"],
                                   run["counters"], run["spans"]),
            "raw_speed": raw_speed_table(run["counter_totals"],
                                         run["gauges"],
                                         run["histograms"],
                                         run["spans"]),
            "sync": sync_table(run["counters"], run["histograms"])}


def diff_runs(a_paths: list[str], b_paths: list[str]) -> dict:
    a, b = summarize_run(a_paths), summarize_run(b_paths)
    counters = {}
    for name in sorted(set(a["counter_totals"]) | set(b["counter_totals"])):
        av = a["counter_totals"].get(name, 0)
        bv = b["counter_totals"].get(name, 0)
        counters[name] = {"a": av, "b": bv, "delta": bv - av}
    spans = {}
    for name in sorted(set(a["spans"]) | set(b["spans"])):
        sa = a["spans"].get(name, {})
        sb = b["spans"].get(name, {})
        spans[name] = {
            "count": {"a": sa.get("count", 0), "b": sb.get("count", 0)},
            "p50_delta": sb.get("p50", float("nan"))
            - sa.get("p50", float("nan")),
            "p95_delta": sb.get("p95", float("nan"))
            - sa.get("p95", float("nan")),
        }
    wire = {}
    wa, wb = a.get("wire", {}), b.get("wire", {})
    for codec in sorted(set(wa) | set(wb)):
        ra = wa.get(codec, {})
        rb = wb.get(codec, {})
        wire[codec] = {
            "frames": {"a": ra.get("frames", 0), "b": rb.get("frames", 0)},
            "wire_bytes": {"a": ra.get("wire_bytes", 0),
                           "b": rb.get("wire_bytes", 0),
                           "delta": rb.get("wire_bytes", 0)
                           - ra.get("wire_bytes", 0)},
            "ratio": {"a": ra.get("ratio", float("nan")),
                      "b": rb.get("ratio", float("nan"))},
        }
    return {"counters": counters, "spans": spans, "wire": wire}


_EVENT_SPANS = ("slo.breach", "slo.recover",
                "autoscaler.scale_up", "autoscaler.scale_down")


def _load_trail(path: str) -> tuple[list[dict], dict | None]:
    """(span records, last snapshot record) of one trail — the raw
    records, unlike :func:`load_run`'s digested durations, because the
    fleet view needs timestamps and labels for the event log."""
    spans: list[dict] = []
    last = None
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue          # torn tail line of a live run
            if rec.get("type") == "span":
                spans.append(rec)
            elif rec.get("type") == "snapshot":
                last = rec
    return spans, last


def _by_label(fam: dict | None, label: str) -> dict:
    out: dict = {}
    for s in (fam or {}).get("samples", []):
        v = (s.get("labels") or {}).get(label)
        if v is not None:
            out[v] = out.get(v, 0) + s.get("value", 0)
    return out


def merge_runs(paths: list[str]) -> dict:
    """Fleet view over one trail per process: merged counters/spans/
    histograms with per-process breakdowns, the SLO and autoscaler
    tables, per-process obs health, and the chronological event log.
    Merging runs through ``obs.agg.FleetRegistry`` — the same math the
    live Collector applies, so this offline report and the in-flight
    SLO engine can never disagree about fleet totals."""
    from distlearn_tpu.obs import agg
    fleet = agg.FleetRegistry()
    sources: list[str] = []
    span_by_src: dict[str, list[dict]] = {}
    events: list[dict] = []
    for path in paths:
        src = os.path.basename(path)
        if src in span_by_src:          # two processes, one basename
            src = path
        sources.append(src)
        spans, snap = _load_trail(path)
        span_by_src[src] = spans
        if snap is not None:
            fleet.ingest(snap, source=src)
        for rec in spans:
            if rec.get("name") in _EVENT_SPANS:
                events.append({"ts": rec.get("ts", 0.0),
                               "event": rec["name"], "src": src,
                               **(rec.get("labels") or {})})
    events.sort(key=lambda e: e["ts"])
    merged = fleet.merged()

    counters: dict[str, dict] = {}
    gauges: dict[str, dict] = {}
    hists: dict[str, dict] = {}
    for name, fam in sorted(merged.items()):
        by = fleet.breakdown(name)
        if fam["kind"] == "counter":
            counters[name] = {"total": sum(by.values()), "by": by}
        elif fam["kind"] == "gauge":
            gauges[name] = {"by": by}
        else:
            # a family can be registered but never observed — no samples
            h = fleet.histogram(name) or {"count": 0, "sum": 0.0}
            hists[name] = {
                "count": h["count"],
                "mean": h["sum"] / h["count"] if h["count"]
                else float("nan"),
                "by": by}

    span_tab: dict[str, dict] = {}
    durs_by_name: dict[str, list[float]] = {}
    for src, recs in span_by_src.items():
        for rec in recs:
            name = rec.get("name", "?")
            durs_by_name.setdefault(name, []).append(
                float(rec.get("dur", 0.0)))
            row = span_tab.setdefault(name, {"count": 0, "by": {}})
            row["count"] += 1
            row["by"][src] = row["by"].get(src, 0) + 1
    for name, row in span_tab.items():
        durs = durs_by_name[name]
        row.update(p50=_percentile(durs, 50), p95=_percentile(durs, 95),
                   p99=_percentile(durs, 99), total=sum(durs))

    slo_tab: dict[str, dict] = {}
    ok = _by_label(merged.get("slo_ok"), "slo")
    val = _by_label(merged.get("slo_value"), "slo")
    breaches = _by_label(merged.get("slo_breaches_total"), "slo")
    recoveries = _by_label(merged.get("slo_recoveries_total"), "slo")
    for rule in sorted(set(ok) | set(breaches) | set(recoveries)):
        slo_tab[rule] = {"ok": bool(ok.get(rule, 1)),
                         "value": val.get(rule, float("nan")),
                         "breaches": breaches.get(rule, 0),
                         "recoveries": recoveries.get(rule, 0)}

    scaler_tab: dict = {}
    scale_events = _by_label(
        merged.get("autoscaler_scale_events_total"), "direction")
    if scale_events or "autoscaler_target_size" in gauges:
        scaler_tab = {"events": scale_events,
                      "target_size": max(
                          gauges.get("autoscaler_target_size",
                                     {}).get("by", {}).values(),
                          default=float("nan"))}

    health: dict[str, dict] = {}
    dropped = fleet.breakdown("obs_spans_dropped_total")
    failures = fleet.breakdown("obs_agg_poll_failures_total")
    for src in sources:
        row = {}
        if src in dropped:
            row["spans_dropped"] = dropped[src]
        if src in failures:
            row["poll_failures"] = failures[src]
        if row:
            health[src] = row

    return {"sources": sources, "counters": counters, "gauges": gauges,
            "histograms": hists, "spans": span_tab, "slo": slo_tab,
            "autoscaler": scaler_tab, "obs_health": health,
            "events": events}


def _fmt_by(by: dict) -> str:
    return " ".join(f"{src}={v:g}" for src, v in sorted(by.items()))


def _print_merge(doc: dict):
    print(f"fleet of {len(doc['sources'])}: "
          + ", ".join(doc["sources"]) + "\n")
    if doc["spans"]:
        print(f"{'span':<32} {'count':>7} {'p50':>10} {'p95':>10} "
              f"{'p99':>10}  per-process")
        for name, row in sorted(doc["spans"].items()):
            print(f"{name:<32} {row['count']:>7} "
                  f"{_fmt_s(row['p50']):>10} {_fmt_s(row['p95']):>10} "
                  f"{_fmt_s(row['p99']):>10}  {_fmt_by(row['by'])}")
        print()
    if doc["counters"]:
        print(f"{'counter':<40} {'fleet':>10}  per-process")
        for name, row in doc["counters"].items():
            print(f"{name:<40} {row['total']:>10g}  "
                  f"{_fmt_by(row['by'])}")
        print()
    if doc["histograms"]:
        print(f"{'histogram':<40} {'count':>8} {'mean':>10}  per-process")
        for name, row in doc["histograms"].items():
            print(f"{name:<40} {row['count']:>8g} "
                  f"{_fmt_s(row['mean']):>10}  {_fmt_by(row['by'])}")
        print()
    if doc["slo"]:
        print(f"{'slo rule':<24} {'state':>8} {'value':>10} "
              f"{'breaches':>9} {'recoveries':>11}")
        for rule, row in doc["slo"].items():
            state = "ok" if row["ok"] else "BREACH"
            print(f"{rule:<24} {state:>8} {row['value']:>10.4g} "
                  f"{row['breaches']:>9g} {row['recoveries']:>11g}")
        print()
    if doc["autoscaler"]:
        a = doc["autoscaler"]
        ev = " ".join(f"{d}={v:g}"
                      for d, v in sorted(a["events"].items()))
        print(f"autoscaler: target_size={a['target_size']:g} "
              f"events[{ev}]")
        print()
    for src, row in doc["obs_health"].items():
        if row.get("spans_dropped"):
            print(f"WARNING: {src} dropped {row['spans_dropped']:g} span "
                  "records (ring wrapped) — span tables undercount")
        if row.get("poll_failures"):
            print(f"WARNING: {src} had {row['poll_failures']:g} collector "
                  "poll failures — fleet totals may lag")
    if doc["events"]:
        print("fleet events:")
        t0 = doc["events"][0]["ts"]
        for e in doc["events"]:
            extra = " ".join(f"{k}={v}" for k, v in sorted(e.items())
                             if k not in ("ts", "event", "src"))
            print(f"  +{e['ts'] - t0:8.3f}s  {e['event']:<22} {extra}  "
                  f"[{e['src']}]")


def _fmt_s(v: float) -> str:
    if v != v:
        return "nan"
    if abs(v) >= 1.0:
        return f"{v:.3f}s"
    if abs(v) >= 1e-3:
        return f"{v * 1e3:.2f}ms"
    return f"{v * 1e6:.1f}us"


def _print_summary(doc: dict):
    dropped = doc["counter_totals"].get("obs_spans_dropped_total", 0)
    if dropped:
        print(f"WARNING: the span ring dropped {dropped:g} records "
              "(trail truncated) — span tables undercount\n")
    if doc["spans"]:
        print(f"{'span':<40} {'count':>7} {'p50':>10} {'p95':>10} "
              f"{'p99':>10} {'total':>10} {'err':>5}")
        for name, row in doc["spans"].items():
            print(f"{name:<40} {row['count']:>7} {_fmt_s(row['p50']):>10} "
                  f"{_fmt_s(row['p95']):>10} {_fmt_s(row['p99']):>10} "
                  f"{_fmt_s(row['total']):>10} {row['errors']:>5}")
        print()
    if doc["counters"]:
        print("counters:")
        for key, v in doc["counters"].items():
            print(f"  {key} = {v:g}")
        for name, v in doc["counter_totals"].items():
            if name + "{" in "".join(doc["counters"]):
                print(f"  {name} (sum over labels) = {v:g}")
        print()
    if doc["gauges"]:
        print("gauges:")
        for key, v in doc["gauges"].items():
            print(f"  {key} = {v:g}")
        print()
    if doc["histograms"]:
        print("histograms:")
        for key, row in doc["histograms"].items():
            print(f"  {key}: count={row['count']} "
                  f"mean={_fmt_s(row['mean'])} sum={_fmt_s(row['sum'])}")
        print()
    if doc.get("wire"):
        print(f"{'packed wire':<12} {'frames':>8} {'wire bytes':>14} "
              f"{'logical bytes':>14} {'ratio':>7}")
        for codec, row in doc["wire"].items():
            print(f"{codec:<12} {row['frames']:>8g} "
                  f"{row['wire_bytes']:>14g} {row['logical_bytes']:>14g} "
                  f"{row['ratio']:>7.2f}")
        print()
    if doc.get("codec"):
        cd = doc["codec"]
        if cd.get("stripes"):
            print(f"{'codec stripe':<12} {'encodes':>9} {'encode mean':>13} "
                  f"{'applies':>9} {'apply mean':>12}")
            for shard, row in cd["stripes"].items():
                print(f"{shard:<12} {row['encodes']:>9g} "
                      f"{_fmt_s(row['encode_mean']):>13} "
                      f"{row['applies']:>9g} "
                      f"{_fmt_s(row['apply_mean']):>12}")
        if cd.get("zero_copy"):
            z = cd["zero_copy"]
            print(f"zero-copy frames: hit={z['hit']:g} miss={z['miss']:g} "
                  f"hit_ratio={z['hit_ratio']:.2f}")
        print()
    if doc.get("shards"):
        print(f"{'shard':<8} {'legs':>8} {'wire bytes':>14} "
              f"{'applies':>9} {'apply mean':>12}")
        for shard, row in doc["shards"].items():
            print(f"{shard:<8} {row['legs']:>8g} "
                  f"{row['wire_bytes']:>14g} {row['applies']:>9g} "
                  f"{_fmt_s(row['apply_mean']):>12}")
        print()
    if doc.get("sync"):
        print(f"{'sync backend':<14} {'rounds':>7} {'host bytes':>13} "
              f"{'logical bytes':>14} {'host/round':>12} {'reduc':>7} "
              f"{'mean':>10} {'syncs/s':>9}")
        for backend, row in doc["sync"].items():
            sps = row.get("syncs_per_s", float("nan"))
            print(f"{backend:<14} {row['rounds']:>7g} "
                  f"{row['host_leg_bytes']:>13g} "
                  f"{row['logical_bytes']:>14g} "
                  f"{row['host_bytes_per_round']:>12g} "
                  f"{row['host_reduction']:>7.1f} "
                  f"{_fmt_s(row.get('sync_mean', float('nan'))):>10} "
                  f"{sps:>9.1f}")
        print()
    if doc.get("failover"):
        fo = doc["failover"]
        print("failover:")
        for col in ("evictions", "rejoins", "redials", "promotions",
                    "stale_refusals", "ckpt_saves", "ckpt_restores"):
            if col in fo:
                print(f"  {col} = {fo[col]:g}")
        for outcome, v in fo.get("replays", {}).items():
            print(f"  replays[{outcome}] = {v:g}")
        for name, row in fo.get("latency", {}).items():
            print(f"  {name}: count={row['count']} "
                  f"p50={_fmt_s(row['p50'])} p99={_fmt_s(row['p99'])}")
        print()
    if doc.get("membership"):
        mb = doc["membership"]
        print("membership:")
        for col in ("joins", "join_failures", "fleet_size"):
            if col in mb:
                print(f"  {col} = {mb[col]:g}")
        for outcome, v in mb.get("leaves", {}).items():
            print(f"  leaves[{outcome}] = {v:g}")
        for cid, v in mb.get("adaptive_tau", {}).items():
            print(f"  adaptive_tau[cid={cid}] = {v:g}")
        for name, row in mb.get("latency", {}).items():
            print(f"  {name}: count={row['count']} "
                  f"p50={_fmt_s(row['p50'])} p99={_fmt_s(row['p99'])}")
        print()
    if doc.get("serving"):
        sv = doc["serving"]
        print("serving:")
        for outcome, v in sv.get("requests", {}).items():
            print(f"  requests[{outcome}] = {v:g}")
        if "tokens" in sv:
            print(f"  tokens = {sv['tokens']:g}")
        if sv.get("latency"):
            print(f"  {'':<8} {'count':>7} {'p50':>10} {'p95':>10} "
                  f"{'p99':>10}")
            for col in ("ttft", "tpot", "prefill", "tick"):
                row = sv["latency"].get(col)
                if row:
                    print(f"  {col:<8} {row['count']:>7} "
                          f"{_fmt_s(row['p50']):>10} "
                          f"{_fmt_s(row['p95']):>10} "
                          f"{_fmt_s(row['p99']):>10}")
        print()
    if doc.get("router"):
        rt = doc["router"]
        print("router:")
        for replica, v in rt.get("dispatch", {}).items():
            print(f"  dispatch[{replica}] = {v:g}")
        for col in ("retries", "hedges", "sheds", "fence_violations"):
            if col in rt:
                print(f"  {col} = {rt[col]:g}")
        for name, row in rt.get("latency", {}).items():
            print(f"  {name}: count={row['count']} "
                  f"p50={_fmt_s(row['p50'])} p99={_fmt_s(row['p99'])}")
        print()
    if doc.get("raw_speed"):
        rs = doc["raw_speed"]
        print("raw speed (prefix cache / speculation):")
        pc = rs.get("prefix_cache")
        if pc:
            for col in ("hits", "misses", "evictions", "pages_retained"):
                if col in pc:
                    print(f"  cache {col} = {pc[col]:g}")
            if "hit_rate" in pc:
                print(f"  cache hit_rate = {pc['hit_rate']:.2f}")
        sp = rs.get("speculation")
        if sp:
            print(f"  spec accepted_tokens_per_tick = "
                  f"{sp['accepted_tokens_per_tick']:.2f} "
                  f"(over {sp['verify_slot_ticks']:g} slot-ticks, "
                  f"{sp['verify_dispatches']:g} verify dispatches)")
        if "prefill_chunks" in rs:
            print(f"  prefill_chunks = {rs['prefill_chunks']:g}")
        for name, row in rs.get("latency", {}).items():
            print(f"  {name}: count={row['count']} "
                  f"p50={_fmt_s(row['p50'])} p99={_fmt_s(row['p99'])}")


def _print_diff(doc: dict):
    if doc["counters"]:
        print(f"{'counter':<44} {'a':>12} {'b':>12} {'delta':>12}")
        for name, row in doc["counters"].items():
            print(f"{name:<44} {row['a']:>12g} {row['b']:>12g} "
                  f"{row['delta']:>+12g}")
        print()
    if doc["spans"]:
        print(f"{'span':<40} {'count a/b':>12} {'dp50':>10} {'dp95':>10}")
        for name, row in doc["spans"].items():
            cnt = f"{row['count']['a']}/{row['count']['b']}"
            print(f"{name:<40} {cnt:>12} {_fmt_s(row['p50_delta']):>10} "
                  f"{_fmt_s(row['p95_delta']):>10}")
        print()
    if doc.get("wire"):
        print(f"{'packed wire':<12} {'frames a/b':>12} "
              f"{'dwire bytes':>14} {'ratio a/b':>14}")
        for codec, row in doc["wire"].items():
            cnt = f"{row['frames']['a']:g}/{row['frames']['b']:g}"
            ratio = f"{row['ratio']['a']:.2f}/{row['ratio']['b']:.2f}"
            print(f"{codec:<12} {cnt:>12} "
                  f"{row['wire_bytes']['delta']:>+14g} {ratio:>14}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="diststat", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd")
    ps = sub.add_parser("summarize", help="aggregate one run's JSONL trail")
    ps.add_argument("paths", nargs="+")
    ps.add_argument("--format", choices=("text", "json"), default="text")
    pd = sub.add_parser("diff", help="counter/latency deltas of two runs")
    pd.add_argument("a")
    pd.add_argument("b")
    pd.add_argument("--format", choices=("text", "json"), default="text")
    pm = sub.add_parser("merge", help="fleet view: one trail per "
                                      "process, per-process breakdowns")
    pm.add_argument("paths", nargs="+")
    pm.add_argument("--format", choices=("text", "json"), default="text")
    args = p.parse_args(argv)
    if args.cmd is None:
        p.print_usage(sys.stderr)
        return 2
    try:
        if args.cmd == "summarize":
            doc = summarize_run(args.paths)
        elif args.cmd == "merge":
            doc = merge_runs(args.paths)
        else:
            doc = diff_runs([args.a], [args.b])
    except OSError as e:
        print(f"diststat: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps(doc, indent=2, sort_keys=True))
    elif args.cmd == "summarize":
        _print_summary(doc)
    elif args.cmd == "merge":
        _print_merge(doc)
    else:
        _print_diff(doc)
    return 0


if __name__ == "__main__":
    sys.exit(main())
