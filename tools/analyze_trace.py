#!/usr/bin/env python
"""Attribute device step time from a jax.profiler trace.

Reads the Chrome-trace JSON (``*.trace.json.gz``) a ``--profile`` run
wrote (examples/lm.py, tools/profile_resnet.py) and prints device time
grouped by output-shape signature and fusion kind — the evidence behind
docs/PERF.md's utilization-gap table (a measured
matmul/attention/elementwise/update breakdown rather than an
arithmetic-intensity argument).

Works with the stdlib only: the xplane.pb route needs
tensorboard_plugin_profile, whose generated protos clash with this
environment's protobuf/TF versions, while the chrome trace carries the
same per-op durations (`pid` = device, `tid` "XLA Ops" lane) plus each
op's HLO `long_name` for shape-based classification.

Usage:
    python tools/analyze_trace.py /tmp/prof4096 [--top 25]
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys


def find_trace(log_dir: str) -> str:
    hits = sorted(glob.glob(os.path.join(log_dir, "**", "*.trace.json.gz"),
                            recursive=True))
    if not hits:
        sys.exit(f"no *.trace.json.gz under {log_dir}")
    return hits[-1]


def load_device_ops(path: str):
    """[(name, kind, shape_sig, dur_us)] for the device's 'XLA Ops' lane."""
    data = json.load(gzip.open(path, "rt"))
    ev = data["traceEvents"] if isinstance(data, dict) else data
    device_pids = set()
    op_lanes = {}                     # pid -> tid of the "XLA Ops" lane
    for e in ev:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name" and "device:" in str(
                e.get("args", {}).get("name", "")).lower() \
                and "cpu" not in str(e["args"]["name"]).lower():
            device_pids.add(e["pid"])
        if e.get("name") == "thread_name" \
                and e.get("args", {}).get("name") == "XLA Ops":
            op_lanes[e["pid"]] = e["tid"]
    ops = []
    for e in ev:
        if e.get("ph") != "X":
            continue
        pid = e.get("pid")
        if pid not in device_pids or e.get("tid") != op_lanes.get(pid):
            continue
        ln = (e.get("args") or {}).get("long_name", "")
        m = re.match(r"%\S+ = (\(?[a-z0-9]+\[[^\]]*\])", ln)
        sig = re.sub(r"\{[^}]*\}", "", m.group(1)) if m else "?"
        kind = e["name"].split(".")[0]
        ops.append((e["name"], kind, sig, float(e.get("dur", 0.0))))
    return ops


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("log_dir")
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args()

    ops = load_device_ops(find_trace(args.log_dir))
    if not ops:
        sys.exit("no device XLA-op events in trace (profile a real step)")
    total = sum(t for *_, t in ops)
    by_sig = collections.Counter()
    for _, kind, sig, t in ops:
        by_sig[(kind, sig)] += t
    print(f"device XLA-op time: {total/1e3:.1f} ms over the trace window "
          f"({len(ops)} op executions)")
    print(f"\n== top {args.top} (fusion kind, output signature) ==")
    for (kind, sig), t in by_sig.most_common(args.top):
        print(f"  {t/1e3:8.1f} ms {100*t/total:5.1f}%  {kind:28s} {sig}")


if __name__ == "__main__":
    main()
