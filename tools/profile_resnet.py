#!/usr/bin/env python
"""Capture a jax.profiler trace of the ResNet-50 fused SGD step.

The LM example has ``--profile``; this gives the ResNet bench config
(BASELINE.md stretch model) the same treatment so the utilization-gap
analysis (docs/PERF.md) rests on measured op breakdowns for both model
families.

Usage:
    python tools/profile_resnet.py /tmp/prof_resnet [--batch 256] [--steps 8]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))        # repo root (run from anywhere)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("log_dir")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=8)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import random
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distlearn_tpu.models.resnet import resnet50
    from distlearn_tpu.parallel.mesh import MeshTree
    from distlearn_tpu.train import build_sgd_step, init_train_state
    from distlearn_tpu.utils.profiling import trace

    tree = MeshTree(num_nodes=len(jax.devices()))
    platform = jax.devices()[0].platform
    model = resnet50(
        compute_dtype=jnp.bfloat16 if platform == "tpu" else None)
    ts = init_train_state(model, tree, random.PRNGKey(0), 1000)
    step = build_sgd_step(model, tree, lr=0.1)
    rs = np.random.RandomState(0)
    sh = NamedSharding(tree.mesh, P("data"))
    bx = jax.device_put(rs.randn(args.batch, 224, 224, 3)
                        .astype(np.float32), sh)
    by = jax.device_put(rs.randint(0, 1000, (args.batch,))
                        .astype(np.int32), sh)

    for _ in range(3):                       # compile + warmup
        ts, loss = step(ts, bx, by)
    jax.block_until_ready(ts.params)
    with trace(args.log_dir):
        for _ in range(args.steps):
            ts, loss = step(ts, bx, by)
        jax.block_until_ready(ts.params)
    print(f"trace written to {args.log_dir} "
          f"({args.steps} steps, final loss {float(loss):.4f})")


if __name__ == "__main__":
    main()
