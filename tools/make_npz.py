#!/usr/bin/env python
"""Convert standard MNIST / CIFAR-10 dumps to the framework's .npz schema.

The reference trains on real MNIST/CIFAR fetched by torch-dataset from
$HOME-prefixed files (examples/mnist.lua:26-29, examples/Data.lua:7-8).
This environment has no egress, so the examples default to synthetic data;
when real dumps ARE present, this converter produces the `.npz` files the
examples' ``--data`` flag consumes, enabling the accuracy-parity run
(BASELINE.md "accuracy parity").

npz schema (what ``distlearn_tpu.data.load_npz`` reads):
    x : float32 [N, H, W, C]  — NHWC, values in [0, 1]
    y : int32   [N]           — class labels 0..9

Supported inputs (all offline formats):

* MNIST IDX (`python tools/make_npz.py mnist DIR -o mnist.npz`):
  ``train-images-idx3-ubyte[.gz]`` + ``train-labels-idx1-ubyte[.gz]``
  (and ``t10k-*`` for the test split).  Images are zero-padded 28x28 ->
  32x32, matching the 32x32 layout the reference trains on
  (examples/mnist.lua:53 reshapes to 1x32x32).
* CIFAR-10 python batches (`python tools/make_npz.py cifar10 DIR`):
  ``cifar-10-batches-py/data_batch_1..5`` + ``test_batch`` pickles.

Each run writes ``<out>`` (train) and ``<out stem>_test.npz`` (test).
"""

from __future__ import annotations

import argparse
import gzip
import os
import pickle
import struct
import sys

import numpy as np


def _open_maybe_gz(path: str):
    if os.path.exists(path):
        return open(path, "rb")
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    raise FileNotFoundError(f"{path}[.gz] not found")


def _read_idx(path: str) -> np.ndarray:
    """Parse an IDX file (the MNIST wire format: magic, dims, raw bytes)."""
    with _open_maybe_gz(path) as fh:
        magic = struct.unpack(">I", fh.read(4))[0]
        dtype_code, ndim = (magic >> 8) & 0xFF, magic & 0xFF
        if dtype_code != 0x08:
            raise ValueError(f"{path}: only ubyte IDX supported, got "
                             f"type 0x{dtype_code:02x}")
        shape = struct.unpack(f">{ndim}I", fh.read(4 * ndim))
        data = np.frombuffer(fh.read(), dtype=np.uint8)
    return data.reshape(shape)


def convert_mnist(src: str, split: str) -> tuple[np.ndarray, np.ndarray]:
    prefix = "train" if split == "train" else "t10k"
    images = _read_idx(os.path.join(src, f"{prefix}-images-idx3-ubyte"))
    labels = _read_idx(os.path.join(src, f"{prefix}-labels-idx1-ubyte"))
    if len(images) != len(labels):
        raise ValueError(f"{len(images)} images vs {len(labels)} labels")
    x = np.zeros((len(images), 32, 32, 1), np.float32)
    x[:, 2:30, 2:30, 0] = images.astype(np.float32) / 255.0   # pad 28->32
    return x, labels.astype(np.int32)


def convert_cifar10(src: str, split: str) -> tuple[np.ndarray, np.ndarray]:
    d = os.path.join(src, "cifar-10-batches-py")
    if not os.path.isdir(d):
        d = src
    names = [f"data_batch_{i}" for i in range(1, 6)] if split == "train" \
        else ["test_batch"]
    xs, ys = [], []
    for name in names:
        with _open_maybe_gz(os.path.join(d, name)) as fh:
            batch = pickle.load(fh, encoding="bytes")
        xs.append(np.asarray(batch[b"data"], np.uint8))
        ys.append(np.asarray(batch[b"labels"], np.int64))
    x = np.concatenate(xs).reshape(-1, 3, 32, 32)     # CHW in the pickles
    x = x.transpose(0, 2, 3, 1).astype(np.float32) / 255.0   # -> NHWC
    return x, np.concatenate(ys).astype(np.int32)


_CONVERTERS = {"mnist": convert_mnist, "cifar10": convert_cifar10}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("dataset", choices=sorted(_CONVERTERS))
    p.add_argument("src", help="directory holding the raw dump")
    p.add_argument("-o", "--out", default="",
                   help="output .npz (default: <dataset>.npz)")
    args = p.parse_args(argv)
    out = args.out or f"{args.dataset}.npz"
    stem, ext = os.path.splitext(out)
    conv = _CONVERTERS[args.dataset]

    x, y = conv(args.src, "train")
    np.savez_compressed(out, x=x, y=y)
    print(f"wrote {out}: x {x.shape} {x.dtype}, y {y.shape} "
          f"({len(np.unique(y))} classes)")
    try:
        xt, yt = conv(args.src, "test")
    except FileNotFoundError as e:
        print(f"no test split converted ({e})", file=sys.stderr)
        return 0
    np.savez_compressed(f"{stem}_test{ext}", x=xt, y=yt)
    print(f"wrote {stem}_test{ext}: x {xt.shape}, y {yt.shape}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
