#!/usr/bin/env python
"""Render the tester's JSONL error log as an error-rate plot.

The reference's tester drives ``optim.Logger`` + gnuplot curves
(/root/reference/examples/EASGD_tester.lua:47,161-165); here the tester
writes JSONL (utils.logging.MetricsLogger) and this tool renders it —
the plotting half the JSONL replaced.

Usage:
    python tools/plot_errors.py ckpt/tester.jsonl [-o errors.png]

Any numeric fields ending in ``_error``/``_err`` are plotted against
``round`` (falling back to record order).  Requires matplotlib (present
in this environment); exits with a clear message otherwise.
"""

from __future__ import annotations

import argparse
import json
import sys


def load(path: str):
    rows = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                print(f"skipping undecodable line: {line[:80]}",
                      file=sys.stderr)
    if not rows:
        sys.exit(f"no records in {path}")
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("jsonl")
    ap.add_argument("-o", "--out", default=None,
                    help="output image (default: <jsonl>.png)")
    args = ap.parse_args()

    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        sys.exit("matplotlib is required to render plots; the JSONL "
                 "itself is the portable artifact")

    rows = load(args.jsonl)
    keys = sorted({k for r in rows for k in r
                   if (k.endswith("_error") or k.endswith("_err"))
                   and isinstance(r[k], (int, float))})
    if not keys:
        sys.exit("no *_error/*_err numeric fields found")
    xs = [r.get("round", i) for i, r in enumerate(rows)]

    fig, ax = plt.subplots(figsize=(7, 4.2))
    for k in keys:
        ys = [r.get(k) for r in rows]
        ax.plot(xs, ys, marker="o", markersize=3, linewidth=1.2,
                label=k.replace("_", " "))
    ax.set_xlabel("evaluation round")
    ax.set_ylabel("error rate")
    ax.set_ylim(bottom=0)
    ax.grid(True, alpha=0.3)
    ax.legend()
    ax.set_title("EASGD tester error rates")
    out = args.out or (args.jsonl.rsplit(".", 1)[0] + ".png")
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print(f"wrote {out} ({len(rows)} records, fields: {', '.join(keys)})")


if __name__ == "__main__":
    main()
